"""ccaudit v3: the whole-program call graph, transitive lock/blocking/
sink summaries, thread-root inference, the race-lockset pass, SARIF
output, the new CLI flags, the baseline-ratchet edge cases the v3 PR
hardens, and the perf guard.

The headline regression tests pin exactly what the v2 analyzer could
NOT see: its call summaries were one hop and same-module (matched by
terminal name), so a lock acquired two calls deep — or in another
module — was invisible to lock-order, blocking-under-lock, and the
protocol sink summaries. ``call_depth=0`` restores the one-hop horizon,
which is how the blindness is demonstrated against the live analyzer.
"""

import json
import subprocess
import sys
import textwrap
import time

import pytest

from tpu_cc_manager.analysis import analyze_paths, repo_root
from tpu_cc_manager.analysis.core import Module, analyze_modules
from tpu_cc_manager.analysis import callgraph, lockset, rules, threads
from tpu_cc_manager.analysis.sarif import to_sarif, validate_sarif


def mods(**sources):
    return [
        Module(f"{name}.py", textwrap.dedent(src))
        for name, src in sources.items()
    ]


def run_many(call_depth=None, **sources):
    return analyze_modules(mods(**sources), call_depth)


def rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------- cross-module ABBA


CROSS_MODULE_ABBA = dict(
    moda="""
        import threading
        import modb
        a_lock = threading.Lock()
        def f():
            with a_lock:
                modb.helper()
        def take_a():
            with a_lock:
                pass
        """,
    modb="""
        import threading
        import moda
        b_lock = threading.Lock()
        def helper():
            with b_lock:
                pass
        def g():
            with b_lock:
                moda.take_a()
        """,
)


def test_cross_module_abba_detected():
    """Both edges of the cycle cross a module boundary through one
    call hop — invisible to v2's same-module summaries, found by the
    whole-program graph."""
    findings = run_many(**CROSS_MODULE_ABBA)
    assert rules_of(findings) == ["lock-order"]
    assert "ABBA" in findings[0].message
    assert "moda.a_lock" in findings[0].message
    assert "modb.b_lock" in findings[0].message


def test_two_hop_abba_same_module():
    """f holds A and reaches B two calls deep; v2's ONE-hop summary
    stopped at the relay. call_depth=0 (the v2 horizon) stays blind,
    the default finds it — the regression pin for the v3 tentpole."""
    src = dict(
        m="""
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def f():
            with a_lock:
                relay()
        def relay():
            deep()
        def deep():
            with b_lock:
                pass
        def g():
            with b_lock:
                with a_lock:
                    pass
        """
    )
    assert rules_of(run_many(**src)) == ["lock-order"]
    assert run_many(call_depth=0, **src) == []


def test_depth_bound_is_an_escape_hatch():
    # the lock sits 2 edges beyond the direct callee: call_depth=1
    # cuts the chain, the default horizon finds it
    src = dict(
        m="""
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def f():
            with a_lock:
                r1()
        def r1():
            r2()
        def r2():
            r3()
        def r3():
            with b_lock:
                pass
        def g():
            with b_lock:
                with a_lock:
                    pass
        """
    )
    assert rules_of(run_many(**src)) == ["lock-order"]
    assert run_many(call_depth=1, **src) == []


def test_self_method_call_hop_still_resolves():
    # the v2 self.-method hop keeps working under the new resolver
    findings = run_many(
        m="""
        import threading

        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()

            def take_b(self):
                with self._b_lock:
                    pass

            def f(self):
                with self._a_lock:
                    self.take_b()

            def g(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """
    )
    assert rules_of(findings) == ["lock-order"]


# --------------------------------------- transitive blocking-under-lock


def test_blocking_two_hops_under_lock_flagged():
    findings = run_many(
        m="""
        import threading, time
        lock = threading.Lock()
        def a():
            with lock:
                b()
        def b():
            c()
        def c():
            time.sleep(1)
        """
    )
    assert rules_of(findings) == ["blocking-under-lock"]
    assert "time.sleep" in findings[0].message
    # anchored at the call under the lock, not at the sleep
    assert findings[0].text == "b()"


def test_blocking_call_site_pragma_suppresses_transitive():
    assert run_many(
        m="""
        import threading, time
        lock = threading.Lock()
        def a():
            with lock:
                b()  # ccaudit: allow-blocking-under-lock(b only sleeps in tests)
        def b():
            time.sleep(1)
        """
    ) == []


def test_sanctioned_blocking_site_not_reported_transitively():
    # a pragma on the SITE sanctions every path that reaches it
    assert run_many(
        m="""
        import threading, time
        lock = threading.Lock()
        def a():
            with lock:
                b()
        def b():
            time.sleep(1)  # ccaudit: allow-blocking-under-lock(bounded 5ms poll)
        """
    ) == []


def test_executor_wait_reached_through_call_flagged():
    findings = run_many(
        m="""
        import threading
        lock = threading.Lock()
        def collect(futures):
            return [f.result() for f in futures]
        def bad(futures):
            with lock:
                return collect(futures)
        """
    )
    assert rules_of(findings) == ["blocking-under-lock"]


# --------------------------------------- transitive protocol summaries


def test_cross_module_sink_summary_flags_raw_literal():
    """The raw literal sits two resolvable calls (and one module
    boundary) away from the label-write sink — v2's same-module one-hop
    summary never saw it."""
    findings = run_many(
        m1="""
        def publish(kube, node, value):
            set_cc_mode_state_label(kube, node, value)
        """,
        m2="""
        import m1
        def relay(kube, node, v):
            m1.publish(kube, node, v)
        def bad(kube, node):
            relay(kube, node, "failed")
        """,
    )
    assert rules_of(findings) == ["protocol-literal"]
    assert findings[0].file == "m2.py"


def test_cross_module_sink_summary_constant_passes():
    assert run_many(
        m1="""
        def publish(kube, node, value):
            set_cc_mode_state_label(kube, node, value)
        """,
        m2="""
        import m1
        from tpu_cc_manager.modes import STATE_FAILED
        def good(kube, node):
            m1.publish(kube, node, STATE_FAILED)
        """,
    ) == []


# ------------------------------------------------ thread-root inference


def _graph_and_roots(**sources):
    audits = [rules.audit_module(m) for m in mods(**sources)]
    graph = callgraph.build(audits)
    return graph, threads.infer_roots(audits, graph)


def test_thread_roots_inferred():
    graph, roots = _graph_and_roots(
        m="""
        import threading
        from http.server import BaseHTTPRequestHandler

        def top():
            pass

        class S:
            def start(self):
                threading.Thread(target=self._run).start()
                threading.Thread(target=top).start()
            def _run(self):
                pass

        def spawn(pool, items):
            def worker(i):
                pass
            for i in items:
                pool.submit(worker, i)

        class H(BaseHTTPRequestHandler):
            def do_GET(self):
                pass
        """
    )
    kinds = {q: r.kind for q, r in roots.items()}
    assert kinds["m.S._run"] == "thread"
    assert kinds["m.top"] == "thread"
    assert kinds["m.spawn.worker"] == "submit"
    assert roots["m.spawn.worker"].self_concurrent
    assert kinds["m.H.do_GET"] == "handler"
    assert roots["m.H.do_GET"].self_concurrent


def test_typed_local_thread_target_resolves():
    graph, roots = _graph_and_roots(
        m="""
        import threading
        class Agent:
            def run(self):
                pass
        def main():
            agent = Agent()
            threading.Thread(target=agent.run).start()
        """
    )
    assert "m.Agent.run" in roots
    # fresh instance per spawn: the root does not race itself
    assert not roots["m.Agent.run"].self_concurrent


def test_subsumed_root_is_not_a_second_context():
    # scan_once is spawned AND called from the run loop: one code path,
    # not two racing threads
    graph, roots = _graph_and_roots(
        m="""
        import threading
        class C:
            def run(self):
                self.scan_once()
            def scan_once(self):
                pass
        def main():
            c = C()
            threading.Thread(target=c.run).start()
            threading.Thread(target=c.scan_once).start()
        """
    )
    ctx = threads.contexts(graph, roots)
    assert ctx["m.C.scan_once"] == {"m.C.run"}


# ----------------------------------------------------- race-lockset


def test_unguarded_write_from_two_roots_flagged():
    findings = run_many(
        m="""
        import threading
        class S:
            def start(self):
                threading.Thread(target=self._w1).start()
                threading.Thread(target=self._w2).start()
            def _w1(self):
                self.counter += 1
            def _w2(self):
                self.counter += 1
        """
    )
    assert rules_of(findings) == ["race-lockset", "race-lockset"]
    assert "no lock held" in findings[0].message


def test_consistently_guarded_writes_pass():
    assert run_many(
        m="""
        import threading
        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.counter = 0
            def start(self):
                threading.Thread(target=self._w1).start()
                threading.Thread(target=self._w2).start()
            def _w1(self):
                with self._lock:
                    self.counter += 1
            def _w2(self):
                with self._lock:
                    self.counter += 1
        """
    ) == []


def test_inconsistent_locksets_flagged():
    findings = run_many(
        m="""
        import threading
        class S:
            def __init__(self):
                self._a_lock = threading.Lock()
                self._b_lock = threading.Lock()
                self.counter = 0
            def start(self):
                threading.Thread(target=self._w1).start()
                threading.Thread(target=self._w2).start()
            def _w1(self):
                with self._a_lock:
                    self.counter += 1
            def _w2(self):
                with self._b_lock:
                    self.counter += 1
        """
    )
    assert rules_of(findings) == ["race-lockset", "race-lockset"]
    assert "share no common lock" in findings[0].message


def test_caller_held_lock_recognized():
    # the _locked-suffix convention: the guard lives at every call site
    assert run_many(
        m="""
        import threading
        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def start(self):
                threading.Thread(target=self._w1).start()
                threading.Thread(target=self._w2).start()
            def _w1(self):
                with self._lock:
                    self._bump_locked()
            def _w2(self):
                with self._lock:
                    self._bump_locked()
            def _bump_locked(self):
                self.n += 1
        """
    ) == []


def test_one_unguarded_caller_defeats_caller_held():
    findings = run_many(
        m="""
        import threading
        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def start(self):
                threading.Thread(target=self._w1).start()
                threading.Thread(target=self._w2).start()
            def _w1(self):
                with self._lock:
                    self._bump_locked()
            def _w2(self):
                self._bump_locked()
            def _bump_locked(self):
                self.n += 1
        """
    )
    assert rules_of(findings) == ["race-lockset"]


def test_reads_only_sharing_passes():
    assert run_many(
        m="""
        import threading
        class S:
            def __init__(self):
                self.mode = "off"
            def start(self):
                threading.Thread(target=self._w1).start()
                threading.Thread(target=self._w2).start()
            def _w1(self):
                return self.mode
            def _w2(self):
                return self.mode
        """
    ) == []


def test_init_before_spawn_recognized():
    # writes in __init__ and pre-start() writes in the spawning
    # function happen-before the thread exists
    assert run_many(
        m="""
        import threading
        class S:
            def __init__(self):
                self.n = 0
            def start(self):
                self.n = 1
                t = threading.Thread(target=self._w)
                t.start()
            def _w(self):
                return self.n
        """
    ) == []


def test_single_writer_thread_with_readers_passes():
    # one writer thread + unguarded readers: a GIL-atomic store, not a
    # lost update — the deliberate deviation from Eraser
    assert run_many(
        m="""
        import threading
        class S:
            def start(self):
                threading.Thread(target=self._w).start()
            def _w(self):
                self.count = 1
            def peek(self):
                return self.count
        """
    ) == []


def test_race_lockset_pragma_suppresses():
    assert run_many(
        m="""
        import threading
        class S:
            def start(self):
                threading.Thread(target=self._w1).start()
                threading.Thread(target=self._w2).start()
            def _w1(self):
                self.warned = True  # ccaudit: allow-race-lockset(monotonic latch; a lost update costs one duplicate log)
            def _w2(self):
                self.warned = True  # ccaudit: allow-race-lockset(monotonic latch; a lost update costs one duplicate log)
        """
    ) == []


def test_outer_alias_attributes_tracked():
    # the webhook idiom: a nested handler class mutating the enclosing
    # server instance through an `outer = self` closure alias
    findings = run_many(
        m="""
        from http.server import BaseHTTPRequestHandler
        class Srv:
            def __init__(self):
                outer = self
                self.hits = 0
                class H(BaseHTTPRequestHandler):
                    def do_GET(self):
                        outer.hits += 1
                self.handler = H
        """
    )
    assert rules_of(findings) == ["race-lockset"]
    assert "Srv.hits" in findings[0].message


def test_module_global_written_from_submit_root_flagged():
    findings = run_many(
        m="""
        SEEN = {}
        def work(i):
            SEEN[i] = 1
        def fan_out(pool, items):
            return [pool.submit(work, i) for i in items]
        def report():
            return dict(SEEN)
        """
    )
    assert rules_of(findings) == ["race-lockset"]
    assert "m.SEEN" in findings[0].message


def test_param_linked_callback_inherits_worker_context():
    # the flipexec shape: a bound method handed to a runner whose
    # loop-spawned worker threads call the parameter
    findings = run_many(
        modr="""
        import threading
        def run_all(items, fn):
            def worker(i):
                fn(i)
            for i in items:
                threading.Thread(target=worker).start()
        """,
        mode="""
        import modr
        class Engine:
            def __init__(self):
                self.count = 0
            def go(self, items):
                modr.run_all(items, self._one)
            def _one(self, i):
                self.count += 1
        """,
    )
    assert rules_of(findings) == ["race-lockset"]
    assert "Engine.count" in findings[0].message


def test_queue_linked_callback_inherits_recorder_context():
    # the agent event-recorder shape: push(task) -> queue -> task()
    findings = run_many(
        m="""
        import threading, queue
        class Rec:
            def __init__(self):
                self._q = queue.Queue(maxsize=64)
                threading.Thread(target=self._loop).start()
            def push(self, task):
                self._q.put(task)
            def _loop(self):
                while True:
                    task = self._q.get()
                    task()
        class User:
            def __init__(self):
                self.n = 0
            def on_fire(self):
                self.n += 1
            def bump(self):
                self.n += 1
        def main():
            r = Rec()
            u = User()
            r.push(u.on_fire)
            u.bump()
        """
    )
    assert rules_of(findings) == ["race-lockset", "race-lockset"]


def test_caller_held_widening_does_not_launder_thread_roots():
    """Review fix: a thread TARGET called under a lock somewhere must
    not have its writes treated as guarded — the Thread-spawn entry
    path holds nothing."""
    findings = run_many(
        m="""
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
            def start(self):
                threading.Thread(target=self._worker).start()
            def kick(self):
                with self._lock:
                    self._worker()
            def bump(self):
                with self._lock:
                    self.count += 1
            def _worker(self):
                self.count += 1
        """
    )
    # both writers participate in the race: the worker's unguarded
    # write AND bump's write under a lock the worker ignores
    assert rules_of(findings) == ["race-lockset", "race-lockset"]
    assert all(f.text == "self.count += 1" for f in findings)


def test_mutually_reachable_roots_still_race():
    """Review fix: two thread roots that call into each other subsume
    each other symmetrically — the kept representative must stay a
    (self-concurrent) context, not vanish with the group."""
    findings = run_many(
        m="""
        import threading
        class C:
            def start(self):
                threading.Thread(target=self.run_a).start()
                threading.Thread(target=self.run_b).start()
            def dispatch(self):
                self.run_a()
                self.run_b()
            def run_a(self):
                self.count += 1
                self.dispatch()
            def run_b(self):
                self.count += 1
                self.dispatch()
        """
    )
    assert rules_of(findings) == ["race-lockset", "race-lockset"]


def test_local_shadow_of_module_global_not_tracked():
    """Review fix: a name assigned in the function without `global` is
    function-local per Python scoping — it never touches the module
    global it shadows."""
    assert run_many(
        m="""
        import threading
        items = []
        def w1():
            items = [1]
            items.append(2)
        def w2():
            items = [3]
            items.append(4)
        def start():
            threading.Thread(target=w1).start()
            threading.Thread(target=w2).start()
        """
    ) == []


def test_global_statement_still_tracked():
    findings = run_many(
        m="""
        import threading
        COUNT = []
        def w1():
            global COUNT
            COUNT = COUNT + [1]
        def w2():
            global COUNT
            COUNT = COUNT + [2]
        def start():
            threading.Thread(target=w1).start()
            threading.Thread(target=w2).start()
        """
    )
    assert rules_of(findings) == ["race-lockset", "race-lockset"]


def test_stale_self_alias_does_not_leak_across_functions():
    """Review fix: `outer = self` in one method must not misattribute
    an unrelated local `outer` in a later function to the class."""
    assert run_many(
        m="""
        import threading
        class Server:
            def __init__(self):
                outer = self
                self.total = 0
            def start(self):
                threading.Thread(target=self._w).start()
                threading.Thread(target=self._w2).start()
            def _w(self):
                return self.total
            def _w2(self):
                return self.total
        def elsewhere(make_thing):
            outer = make_thing()
            outer.total = 5
            outer.total = 6
        """
    ) == []


def test_alias_method_call_propagates_handler_context():
    """Review fix: `outer._bump()` from a handler thread must resolve
    to the enclosing class's method, so the race surfaces even when the
    counter update lives in a helper."""
    findings = run_many(
        m="""
        from http.server import BaseHTTPRequestHandler
        class Srv:
            def __init__(self):
                outer = self
                self.reviews = 0
                class H(BaseHTTPRequestHandler):
                    def do_POST(self):
                        outer._bump()
                self.handler = H
            def _bump(self):
                self.reviews += 1
        """
    )
    assert rules_of(findings) == ["race-lockset"]
    assert "Srv.reviews" in findings[0].message


def test_prespawn_write_in_self_concurrent_function_still_races():
    """Review fix: a pre-.start() write happens-before the SPAWNED
    thread, but two concurrent respawn() invocations still tear it."""
    findings = run_many(
        m="""
        import threading
        class C:
            def respawn(self):
                self.jobs = []
                threading.Thread(target=self._loop).start()
            def _loop(self):
                return self.jobs
        def kick(pool):
            c = C()
            pool.submit(c.respawn)
            pool.submit(c.respawn)
        """
    )
    assert rules_of(findings) == ["race-lockset"]
    assert findings[0].text == "self.jobs = []"


def test_lockgraph_terminal_fallback_for_unknown_receivers():
    """Review fix: v2's same-module terminal-name match survives as the
    fallback when the receiver is unresolvable — previously-detectable
    ABBA cycles on untyped receivers must not vanish."""
    findings = run_many(
        m="""
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def helper():
            with b_lock:
                pass
        def f(obj):
            with a_lock:
                obj.helper()
        def g():
            with b_lock:
                with a_lock:
                    pass
        """
    )
    assert rules_of(findings) == ["lock-order"]


# ------------------------------------------------------------- SARIF


def _sarif_doc(tmp_path, extra_args=()):
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "bad.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
    )
    out = root / "scan.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "--sarif", str(out), *extra_args, "pkg"],
        capture_output=True, text=True,
    )
    return proc, json.loads(out.read_text())


def test_sarif_written_and_schema_valid(tmp_path):
    proc, doc = _sarif_doc(tmp_path)
    assert proc.returncode == 1  # the gate still fails; SARIF rides along
    assert validate_sarif(doc) == []
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "ccaudit"
    (res,) = run["results"]
    assert res["ruleId"] == "swallow"
    assert res["level"] == "error"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "pkg/bad.py"
    assert loc["region"]["startLine"] == 3


def test_sarif_baselined_findings_are_suppressed_notes(tmp_path):
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "bad.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
    )
    baseline = root / "baseline.json"
    baseline.write_text(json.dumps({
        "version": 1,
        "findings": [{
            "rule": "swallow", "file": "pkg/bad.py", "line": 3,
            "text": "except Exception:",
        }],
    }))
    out = root / "scan.sarif"
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "--baseline", str(baseline),
         "--sarif", str(out), "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0
    doc = json.loads(out.read_text())
    assert validate_sarif(doc) == []
    (res,) = doc["runs"][0]["results"]
    assert res["level"] == "note"
    assert res["suppressions"][0]["kind"] == "external"


def test_sarif_stale_baseline_entries_reported():
    doc = to_sarif(
        [], [],
        [{"rule": "swallow", "file": "pkg/gone.py", "line": 9,
          "text": "except Exception:"}],
    )
    assert validate_sarif(doc) == []
    (res,) = doc["runs"][0]["results"]
    assert res["ruleId"] == "stale-baseline"
    assert res["level"] == "error"


def test_sarif_validator_rejects_malformed():
    assert validate_sarif({"version": "2.1.0", "runs": "nope"})
    assert validate_sarif({"version": "1.0.0", "runs": []})
    bad = to_sarif(
        [], [],
        [{"rule": "swallow", "file": "x.py", "line": 1, "text": ""}],
    )
    bad["runs"][0]["results"][0]["level"] = "fatal"
    assert any("level" in e for e in validate_sarif(bad))


def test_sarif_repo_scan_validates_with_jsonschema_if_available(tmp_path):
    """Belt and braces: when the environment has jsonschema, check the
    emitted log against an inline schema of the SARIF 2.1.0 required
    subset (the full spec schema is not vendored; CI runs the
    structural validator either way)."""
    jsonschema = pytest.importorskip("jsonschema")
    _, doc = _sarif_doc(tmp_path)
    schema = {
        "type": "object",
        "required": ["version", "runs"],
        "properties": {
            "version": {"const": "2.1.0"},
            "runs": {
                "type": "array",
                "minItems": 1,
                "items": {
                    "type": "object",
                    "required": ["tool"],
                    "properties": {
                        "tool": {
                            "type": "object",
                            "required": ["driver"],
                            "properties": {
                                "driver": {
                                    "type": "object",
                                    "required": ["name"],
                                }
                            },
                        },
                        "results": {
                            "type": "array",
                            "items": {
                                "type": "object",
                                "required": ["message"],
                                "properties": {
                                    "level": {
                                        "enum": ["none", "note",
                                                 "warning", "error"]
                                    },
                                    "message": {
                                        "type": "object",
                                        "required": ["text"],
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    }
    jsonschema.validate(doc, schema)


# ------------------------------------------- CLI + ratchet edge cases


def test_cli_stale_entry_for_renamed_rule_fails_loudly(tmp_path):
    """A baseline entry whose rule id no longer exists (renamed rule)
    must fail as stale — not vanish silently with the rule."""
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "ok.py").write_text("x = 1\n")
    baseline = root / "baseline.json"
    baseline.write_text(json.dumps({
        "version": 1,
        "findings": [{
            "rule": "lock-odor",  # renamed/typo'd rule id
            "file": "pkg/ok.py", "line": 1, "text": "x = 1",
        }],
    }))
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "--baseline", str(baseline), "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "stale-baseline" in proc.stdout
    assert "lock-odor" in proc.stdout


def test_no_manifests_does_not_mask_manifest_drift_entries(tmp_path):
    """--no-manifests skips the cross-check, so a manifest-drift
    baseline entry matches nothing — it must surface as STALE (exit 1),
    not silently keep its slot while the pass is off."""
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "ok.py").write_text("x = 1\n")
    baseline = root / "baseline.json"
    baseline.write_text(json.dumps({
        "version": 1,
        "findings": [{
            "rule": "manifest-drift",
            "file": "deployments/manifests/agent.yaml", "line": 12,
            "text": "tpu.google.com/cc.mod: on",
        }],
    }))
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "--baseline", str(baseline),
         "--no-manifests", "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 1
    assert "stale-baseline" in proc.stdout


def test_cli_call_depth_flag_accepted(tmp_path):
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "ok.py").write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "--call-depth", "0", "pkg"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0


def test_cli_exit_zero_clean_exit_one_on_finding(tmp_path):
    root = tmp_path / "repo"
    (root / "pkg").mkdir(parents=True)
    (root / "pkg" / "ok.py").write_text("x = 1\n")
    clean = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "pkg"],
        capture_output=True, text=True,
    )
    assert clean.returncode == 0
    (root / "pkg" / "bad.py").write_text(
        "try:\n    x = 1\nexcept Exception:\n    pass\n"
    )
    dirty = subprocess.run(
        [sys.executable, "-m", "tpu_cc_manager.analysis",
         "--root", str(root), "pkg"],
        capture_output=True, text=True,
    )
    assert dirty.returncode == 1


# --------------------------------------------------------- perf guard


def test_ccaudit_repo_scan_under_ten_seconds():
    """The transitive passes must not quietly make `make lint`
    unusable: a full default-surface scan (call graph, thread roots,
    locksets, manifests) stays under 10s of wall clock. Best of two
    runs — the suite shares one core with whatever else the sandbox is
    doing, and a single contended run must not flake the guard."""
    best = float("inf")
    for _ in range(2):
        t0 = time.monotonic()
        analyze_paths(repo_root())
        best = min(best, time.monotonic() - t0)
        if best < 10.0:
            break
    assert best < 10.0, f"ccaudit took {best:.1f}s (budget 10s)"


# ---------------------------------------------- lockset internals


def test_location_display_names():
    key = ("tpu_cc_manager.webhook", "attr", "AdmissionServer", "reviews")
    assert lockset._display(key) == "webhook.AdmissionServer.reviews"
    gkey = ("tpu_cc_manager.webhook", "global", "", "_warned")
    assert lockset._display(gkey) == "webhook._warned"
