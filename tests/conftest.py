"""Test harness config.

- Force JAX onto a virtual 8-device CPU mesh (only the fleet-planner tests
  use JAX; everything else is pure control-plane).
- Keep the process-wide device backend isolated between tests.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

# Platform identity is opt-in per test: the default 'auto' would probe
# the GCE metadata server from build_evidence, and on a GCP-hosted CI
# runner that can MINT REAL TOKENS whose instance name contradicts the
# tests' synthetic node names — nondeterministic identity_mismatch
# findings. Tests that want identity set TPU_CC_IDENTITY=fake.
os.environ.setdefault("TPU_CC_IDENTITY", "none")
# same posture for the TEE rung: tests that want attestation set
# TPU_CC_ATTESTATION=fake (plus TPU_CC_TPM_STATE_DIR/TPU_CC_TPM_KEY)
os.environ.setdefault("TPU_CC_ATTESTATION", "none")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest

from tpu_cc_manager.device import base as device_base


def _force_cpu_jax():
    """This image's sitecustomize registers the axon TPU PJRT plugin and
    overrides jax_platforms to 'axon,cpu'; jax.devices() then dials the
    TPU tunnel (minutes). Tests are CPU-only by contract — force it back.
    """
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


_force_cpu_jax()


@pytest.fixture(autouse=True, scope="session")
def _prewarm_planner():
    """Compile the planner's smallest-bucket tick once per session.

    _TICK_CACHE makes the compile once-per-process regardless; paying
    it here (~1s) instead of inside the first controller test keeps
    wall-clock-sensitive assertions (parallel-convergence < 1.0s,
    demotion worker-stop < 5s) measuring what they claim to measure —
    the same reason production runs plan.warmup() at controller start."""
    import numpy as np

    from tpu_cc_manager import plan

    cols = {
        k: np.zeros(plan.BUCKET_MIN_NODES, np.int32)
        for k in ("desired", "observed", "slice_ids", "pool_ids",
                  "taint", "doctor", "ev_ts", "valid")
    }
    plan._tick_fn(plan.BUCKET_MIN_NODES, plan.BUCKET_MIN_POOLS)(
        cols, np.zeros(plan.BUCKET_MIN_POOLS, np.int32)
    )
    # the incremental session's two kernels at the same smallest
    # geometry (ISSUE 19): rebuild eval + delta scatter, so
    # wall-clock-sensitive tests don't pay their first compile either
    sess = plan.TickSession(full_every=0)
    enc = plan.FleetEncoding()
    enc.apply({"metadata": {"name": "_prewarm", "labels": {}}})
    sess.tick(enc)                      # _eval_fn compile (rebuild)
    enc.apply({"metadata": {"name": "_prewarm", "labels": {
        "tpu.google.com/cc.mode": "on"}}})
    sess.tick(enc)                      # _scatter_fn compile (delta)
    sess.tick(enc, force_full=True)     # verify path


@pytest.fixture(autouse=True)
def _reset_device_backend():
    device_base.set_backend(None)
    yield
    device_base.set_backend(None)


@pytest.fixture(autouse=True)
def _reset_identity_caches():
    """The identity module process-caches providers (and their token
    caches) on purpose; between tests that cache is cross-pollution —
    a token minted under one test's key/env must not serve the next."""
    from tpu_cc_manager import identity

    identity._auto_cache = None
    identity._explicit_cache.clear()
    yield
    identity._auto_cache = None
    identity._explicit_cache.clear()


@pytest.fixture(scope="session")
def tls_pki(tmp_path_factory):
    """Self-signed server cert/key for 127.0.0.1 (SAN IP), generated
    with the openssl CLI — shared by the native agent's direct-TLS tests
    and the bash engine's KUBE_API_TLS test. Returns (cert, key) paths;
    the cert doubles as the client's CA file."""
    import shutil
    import subprocess

    if shutil.which("openssl") is None:
        pytest.skip("openssl binary unavailable")
    d = tmp_path_factory.mktemp("pki")
    cert, key = d / "cert.pem", d / "key.pem"
    r = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", str(key), "-out", str(cert), "-days", "1",
         "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"openssl req unavailable: {r.stderr}")
    return str(cert), str(key)
