"""Platform identity binding for the evidence chain (VERDICT r3
missing #1: 'hardware-root the evidence').

The drill these tests run: an adversary who stole the pool evidence
HMAC key can SIGN arbitrary documents — but cannot mint the victim
node's instance identity token (only the node's metadata server /
identity key holder can). Verifiers must therefore flag:

- a signed document carrying NO identity on an identity-bearing pool
  (``identity_missing``),
- a signed document carrying a token that speaks for a DIFFERENT node
  or audience, or fails signature verification
  (``identity_mismatch``),

while uniform identity-less pools (platforms that mint no identities)
stay clean, so nothing breaks off-GCE.
"""

import json
import time

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.evidence import audit_evidence, build_evidence
from tpu_cc_manager.identity import (
    FakePlatformIdentity,
    GceIdentity,
    get_identity_provider,
    judge_identity,
    mint_fake_token,
    verify_token,
)
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node

KEY = b"identity-test-key"


# ------------------------------------------------------------- tokens
def test_token_roundtrip_and_binding():
    tok = mint_fake_token("node-a", KEY)
    assert verify_token(tok, node_name="node-a", key=KEY) == ("ok", "ok")

    # node binding: the same valid token does not speak for node-b
    verdict, detail = verify_token(tok, node_name="node-b", key=KEY)
    assert verdict == "mismatch"
    assert "node-a" in detail and "node-b" in detail

    # audience binding: a token minted for another service is refused
    other = mint_fake_token("node-a", KEY, audience="some-other-svc")
    verdict, _ = verify_token(other, node_name="node-a", key=KEY)
    assert verdict == "mismatch"


def test_token_tamper_and_expiry():
    tok = mint_fake_token("node-a", KEY)
    head, payload, sig = tok.split(".")
    # re-signed with a different key: invalid
    forged = mint_fake_token("node-a", b"wrong-key")
    assert verify_token(forged, node_name="node-a", key=KEY)[0] == "invalid"
    # spliced signature: invalid
    spliced = ".".join([head, payload, forged.split(".")[2]])
    assert verify_token(spliced, node_name="node-a", key=KEY)[0] == "invalid"
    # expired: distinct verdict — staleness, not forgery. But binding
    # failures outrank it: an expired token for the WRONG node is
    # still a mismatch (replay), and a bad signature is still invalid
    old = mint_fake_token("node-a", KEY, now=time.time() - 7200, ttl_s=60)
    assert verify_token(old, node_name="node-a", key=KEY)[0] == "expired"
    assert verify_token(old, node_name="node-b", key=KEY)[0] == "mismatch"
    old_forged = mint_fake_token("node-a", b"wrong-key",
                                 now=time.time() - 7200, ttl_s=60)
    assert verify_token(old_forged, node_name="node-a",
                        key=KEY)[0] == "invalid"
    # garbage
    assert verify_token("not-a-jwt", node_name="node-a",
                        key=KEY)[0] == "invalid"


def test_unverifiable_postures():
    # HS256 token, verifier without the identity key: claims are still
    # bound-checked, the signature verdict degrades honestly
    tok = mint_fake_token("node-a", KEY)
    assert verify_token(tok, node_name="node-a", key=None)[0] == (
        "unverifiable"
    )
    # ...but a bound-check failure outranks unverifiable
    assert verify_token(tok, node_name="node-b", key=None)[0] == "mismatch"


def test_gce_identity_fetch(tmp_path):
    """GceIdentity speaks the metadata-server wire contract: GET the
    identity path with Metadata-Flavor: Google, audience passthrough."""
    import threading
    from http.server import BaseHTTPRequestHandler, HTTPServer

    seen = {}

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            seen["path"] = self.path
            seen["flavor"] = self.headers.get("Metadata-Flavor")
            self.send_response(200)
            self.end_headers()
            self.wfile.write(b"tok-from-metadata\n")

        def log_message(self, *a):
            pass

    srv = HTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        host = f"127.0.0.1:{srv.server_port}"
        tok = GceIdentity(metadata_host=host).token(
            "ignored", audience="aud-x"
        )
    finally:
        srv.shutdown()
    assert tok == "tok-from-metadata"
    assert seen["flavor"] == "Google"
    assert "audience=aud-x" in seen["path"]
    assert "format=full" in seen["path"]


def test_provider_resolution(monkeypatch):
    monkeypatch.setenv("TPU_CC_IDENTITY", "none")
    assert get_identity_provider() is None
    monkeypatch.setenv("TPU_CC_IDENTITY", "fake")
    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", "k")
    assert isinstance(get_identity_provider(), FakePlatformIdentity)
    monkeypatch.setenv("TPU_CC_IDENTITY", "gce")
    assert isinstance(get_identity_provider(), GceIdentity)
    # auto with an unreachable metadata host: resolves to None and the
    # probe outcome is cached (second call does not re-dial)
    monkeypatch.setenv("TPU_CC_IDENTITY", "auto")
    monkeypatch.setenv("TPU_CC_METADATA_HOST", "127.0.0.1:1")
    t0 = time.monotonic()
    assert get_identity_provider(refresh=True) is None
    first = time.monotonic() - t0
    t0 = time.monotonic()
    assert get_identity_provider() is None
    assert time.monotonic() - t0 < first + 0.05


# --------------------------------------------------------- evidence
def _backend(tmp_path, monkeypatch, mode=None):
    from tpu_cc_manager.device.tpu import SysfsTpuBackend

    sysfs = tmp_path / "sysfs"
    d = sysfs / "accel0" / "device"
    d.mkdir(parents=True)
    (d / "vendor").write_text("0x1ae0\n")
    (d / "device").write_text("0x0063\n")
    (tmp_path / "dev").mkdir(exist_ok=True)
    (tmp_path / "dev" / "accel0").write_text("")
    monkeypatch.setenv("TPU_CC_DEVICE_GATING", "none")
    be = SysfsTpuBackend(sysfs_root=str(sysfs),
                         dev_root=str(tmp_path / "dev"),
                         state_dir=str(tmp_path / "state"))
    if mode:
        chips, _ = be.find_tpus()
        be.store.stage(chips[0].path, "cc", mode)
        be.store.commit(chips[0].path)
    return be


def _node_with(name, state, doc):
    return make_node(name, labels={
        L.TPU_ACCELERATOR_LABEL: "v5p", L.CC_MODE_STATE_LABEL: state},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(doc)})


def test_evidence_carries_identity_inside_digest(tmp_path, monkeypatch):
    be = _backend(tmp_path, monkeypatch)
    ident = FakePlatformIdentity(KEY)
    doc = build_evidence("n1", be, key=b"pool", identity_provider=ident)
    assert doc["identity"]["provider"] == "fake"
    assert judge_identity(doc, "n1", key=KEY) == ("ok", "ok")
    # the digest covers the token: swapping it in is detected before
    # identity is ever judged
    from tpu_cc_manager.evidence import verify_evidence

    swapped = dict(doc, identity={
        "provider": "fake",
        "token": mint_fake_token("n1", KEY, now=time.time() + 30)})
    assert verify_evidence(swapped, key=b"pool")[0] is False


def test_stolen_pool_key_without_identity_is_flagged(tmp_path,
                                                     monkeypatch):
    """THE drill: same pool key signs an honest doc (with identity) on
    node A and a forged doc (no identity — the thief can't mint one)
    for node B. The mixed pool exposes the forgery as
    identity_missing."""
    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", KEY.decode())
    be = _backend(tmp_path, monkeypatch, mode="on")
    honest = build_evidence("node-a", be, key=b"pool",
                            identity_provider=FakePlatformIdentity(KEY))
    forged = build_evidence("node-b", be, key=b"pool",
                            identity_provider=None)
    audit = audit_evidence(
        [_node_with("node-a", "on", honest),
         _node_with("node-b", "on", forged)],
        key=b"pool",
    )
    assert audit["identity_missing"] == ["node-b"]
    assert audit["identity_mismatch"] == []
    assert audit["invalid"] == []  # the digest itself verifies fine

    from tpu_cc_manager.fleet import fleet_problems

    problems = fleet_problems({"evidence_audit": audit})
    assert any("identity" in p and "node-b" in p for p in problems)


def test_uniform_identity_outage_detected_across_scans(tmp_path,
                                                       monkeypatch):
    """A fleet-wide metadata outage eventually strips EVERY token
    (tokens age out; the healers republish token-less docs rather than
    keep expired ones). Within one scan that uniform absence is
    indistinguishable from a never-on-GCE pool — so the fleet
    controller carries the tell ACROSS scans: once any scan saw an
    identity-bearing document, a later all-missing pool alarms instead
    of fading back to silence."""
    from tpu_cc_manager.fleet import FleetController

    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", KEY.decode())
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool")
    be = _backend(tmp_path, monkeypatch, mode="on")
    with_id = build_evidence(
        "node-a", be, key=b"pool",
        identity_provider=FakePlatformIdentity(KEY),
    )
    without_id = build_evidence("node-a", be, key=b"pool",
                                identity_provider=None)

    kube = FakeKube()
    kube.add_node(_node_with("node-a", "on", with_id))
    ctrl = FleetController(kube, port=0)
    r1 = ctrl.scan_once()
    assert r1["evidence_audit"]["identity_missing"] == []
    assert r1["evidence_audit"]["identity_seen"] is True

    # the outage: every doc on the pool loses its token
    kube.set_node_annotations(
        "node-a", {L.EVIDENCE_ANNOTATION: json.dumps(without_id)},
    )
    r2 = ctrl.scan_once()
    assert r2["evidence_audit"]["identity_missing"] == ["node-a"]
    # ...and stays flagged on every later scan, not just the first
    assert ctrl.scan_once()["evidence_audit"]["identity_missing"] == \
        ["node-a"]

    # a controller that NEVER saw identity (restart mid-outage, or an
    # off-GCE pool) keeps the old silence — the sticky tell is
    # process-local by design
    fresh = FleetController(kube, port=0)
    assert fresh.scan_once()["evidence_audit"]["identity_missing"] == []
    # the pure function's default is unchanged for direct callers
    audit = audit_evidence([_node_with("node-a", "on", without_id)],
                           key=b"pool")
    assert audit["identity_missing"] == []

    # the latch arms ONLY on a VERIFIED token: the evidence annotation
    # is hostile input, and a single garbage/forged token must not
    # lock a never-on-GCE pool into permanent alarms (it still trips
    # the per-scan mixed-pool heuristic while the doc is present)
    class GarbageProvider:
        provider = "fake"

        def token(self, node_name, audience=None):
            return "eyJub3BlIjo1fQ.garbage.token"

    hostile = build_evidence("node-a", be, key=b"pool",
                             identity_provider=GarbageProvider())
    kube.set_node_annotations(
        "node-a", {L.EVIDENCE_ANNOTATION: json.dumps(hostile)},
    )
    ctrl3 = FleetController(kube, port=0)
    r = ctrl3.scan_once()
    assert r["evidence_audit"]["identity_mismatch"] == ["node-a"]
    assert r["evidence_audit"]["identity_seen"] is False  # not armed
    # the hostile doc heals away; the pool returns to silence
    kube.set_node_annotations(
        "node-a", {L.EVIDENCE_ANNOTATION: json.dumps(without_id)},
    )
    assert ctrl3.scan_once()["evidence_audit"]["identity_missing"] == []


def test_replayed_identity_token_is_mismatch(tmp_path, monkeypatch):
    """The thief gets cleverer: embeds node A's VALID token in the doc
    forged for node B. Node binding in the token claims catches it."""
    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", KEY.decode())
    be = _backend(tmp_path, monkeypatch, mode="on")

    class ReplayingProvider:
        provider = "fake"

        def token(self, node_name, audience=None):
            return mint_fake_token("node-a", KEY)  # always node A's

    forged = build_evidence("node-b", be, key=b"pool",
                            identity_provider=ReplayingProvider())
    audit = audit_evidence([_node_with("node-b", "on", forged)],
                           key=b"pool")
    assert audit["identity_mismatch"] == ["node-b"]


def test_uniform_identityless_pool_is_clean(tmp_path, monkeypatch):
    """Off-GCE pools mint no identities; an all-missing pool is not a
    finding unless TPU_CC_REQUIRE_IDENTITY demands it."""
    be = _backend(tmp_path, monkeypatch, mode="on")
    doc = build_evidence("n1", be, key=b"pool", identity_provider=None)
    nodes = [_node_with("n1", "on", doc)]
    audit = audit_evidence(nodes, key=b"pool")
    assert audit["identity_missing"] == []

    monkeypatch.setenv("TPU_CC_REQUIRE_IDENTITY", "true")
    audit = audit_evidence(nodes, key=b"pool")
    assert audit["identity_missing"] == ["n1"]


def test_rollout_flags_identity_mismatch(tmp_path, monkeypatch):
    """The rollout judge runs the same triage: a member whose evidence
    carries a foreign identity token never counts as converged, and
    the verdict says 'identity'."""
    import threading

    from tpu_cc_manager.rollout import Rollout

    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", KEY.decode())
    monkeypatch.setenv("TPU_CC_EVIDENCE_KEY", "pool")
    be = _backend(tmp_path, monkeypatch, mode="on")

    class ReplayingProvider:
        provider = "fake"

        def token(self, node_name, audience=None):
            return mint_fake_token("victim", KEY)

    forged = build_evidence("copycat", be, key=b"pool",
                            identity_provider=ReplayingProvider())
    kube = FakeKube()
    kube.add_node(make_node("copycat", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(forged)}))

    stop = threading.Event()

    def agent():
        while not stop.is_set():
            labels = kube.get_node("copycat")["metadata"]["labels"]
            want = labels.get(L.CC_MODE_LABEL)
            if want and labels.get(L.CC_MODE_STATE_LABEL) != want:
                kube.set_node_labels(
                    "copycat", {L.CC_MODE_STATE_LABEL: want})
            time.sleep(0.02)

    t = threading.Thread(target=agent, daemon=True)
    t.start()
    try:
        report = Rollout(kube, "on", group_timeout_s=1.5,
                         poll_s=0.05).run()
    finally:
        stop.set()
    (group,) = report.groups
    assert group.outcome == "timeout"
    assert "identity" in group.detail


def test_agent_publishes_identity_bearing_evidence(tmp_path,
                                                   monkeypatch):
    """End-to-end through the agent: TPU_CC_IDENTITY=fake makes every
    reconcile's evidence carry a verifiable identity token."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig

    monkeypatch.setenv("TPU_CC_IDENTITY", "fake")
    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", KEY.decode())
    be = _backend(tmp_path, monkeypatch)
    kube = FakeKube()
    kube.add_node(make_node("id-node"))
    cfg = AgentConfig(node_name="id-node", drain_strategy="none",
                      health_port=0, emit_events=False)
    agent = CCManagerAgent(kube, cfg, backend=be)
    assert agent.reconcile("on") is True
    assert agent.flush_events(timeout=10)
    doc = json.loads(kube.get_node("id-node")["metadata"]["annotations"]
                     [L.EVIDENCE_ANNOTATION])
    assert judge_identity(doc, "id-node", key=KEY) == ("ok", "ok")
    audit = audit_evidence(kube.list_nodes(None), key=None)
    assert audit["identity_mismatch"] == []
    assert audit["identity_missing"] == []


def test_expired_identity_classed_as_staleness_not_forgery(tmp_path,
                                                           monkeypatch):
    """An idle node whose token aged out lands in identity_missing
    (refresh broke), never identity_mismatch (forgery) — an idle fleet
    must not read as under attack."""
    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", KEY.decode())
    be = _backend(tmp_path, monkeypatch, mode="on")

    class StaleProvider:
        provider = "fake"

        def token(self, node_name, audience=None):
            return mint_fake_token(node_name, KEY,
                                   now=time.time() - 7200, ttl_s=60)

    doc = build_evidence("idle-1", be, key=b"pool",
                         identity_provider=StaleProvider())
    audit = audit_evidence([_node_with("idle-1", "on", doc)],
                           key=b"pool")
    assert audit["identity_missing"] == ["idle-1"]
    assert audit["identity_mismatch"] == []


def test_agent_refreshes_evidence_before_token_expiry(tmp_path,
                                                      monkeypatch):
    """No flip ever comes on an idle node: the agent must republish
    evidence from its idle tick before the embedded token's verifier-
    visible expiry, keeping the identity perpetually fresh."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig

    monkeypatch.setenv("TPU_CC_IDENTITY", "fake")
    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", KEY.decode())
    be = _backend(tmp_path, monkeypatch)
    kube = FakeKube()
    kube.add_node(make_node("fresh-node"))
    cfg = AgentConfig(node_name="fresh-node", drain_strategy="none",
                      health_port=0, emit_events=False)
    agent = CCManagerAgent(kube, cfg, backend=be)
    assert agent.reconcile("on") is True
    assert agent.flush_events(timeout=10)
    first = kube.get_node("fresh-node")["metadata"]["annotations"][
        L.EVIDENCE_ANNOTATION]
    # the refresh deadline was computed from the token's exp
    assert agent._evidence_identity_refresh_at is not None

    # idle tick BEFORE the deadline: no republish
    agent._evidence_key_check_due = 0.0
    agent._maybe_repair()
    assert agent.flush_events(timeout=10)
    assert (kube.get_node("fresh-node")["metadata"]["annotations"]
            [L.EVIDENCE_ANNOTATION]) == first

    # cross the deadline (simulate the token aging): republish with a
    # fresh token — and the deadline advances so it doesn't loop
    agent._evidence_identity_refresh_at = time.time() - 1
    agent._evidence_key_check_due = 0.0
    # the provider cache would still serve the cached token (it is not
    # past ITS margin in this accelerated test) — drop it so the
    # rebuild mints fresh, as a real margin-crossing would
    from tpu_cc_manager.identity import get_identity_provider as _gip

    _gip()._cache.clear()
    time.sleep(1.1)  # fake mints at 1 s resolution; force a new iat
    agent._maybe_repair()
    assert agent.flush_events(timeout=10)
    second = kube.get_node("fresh-node")["metadata"]["annotations"][
        L.EVIDENCE_ANNOTATION]
    assert second != first
    assert agent._evidence_identity_refresh_at > time.time() - 1
    doc = json.loads(second)
    assert judge_identity(doc, "fresh-node", key=KEY) == ("ok", "ok")


def test_unkeyed_rollout_judge_still_checks_identity(tmp_path,
                                                     monkeypatch):
    """The audit/rollout lockstep invariant, no_key edition: a rollout
    operator WITHOUT the evidence key still refuses a member whose
    signed document embeds a foreign identity token — node binding in
    the token needs no evidence key to read."""
    import threading

    from tpu_cc_manager.rollout import Rollout

    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", KEY.decode())
    monkeypatch.delenv("TPU_CC_EVIDENCE_KEY", raising=False)
    monkeypatch.delenv("TPU_CC_EVIDENCE_KEY_FILE", raising=False)
    be = _backend(tmp_path, monkeypatch, mode="on")

    class ReplayingProvider:
        provider = "fake"

        def token(self, node_name, audience=None):
            return mint_fake_token("victim", KEY)

    # signed with a key the rollout judge does NOT hold -> no_key path
    forged = build_evidence("copycat", be, key=b"agents-key",
                            identity_provider=ReplayingProvider())
    kube = FakeKube()
    kube.add_node(make_node("copycat", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"},
        annotations={L.EVIDENCE_ANNOTATION: json.dumps(forged)}))

    stop = threading.Event()

    def agent():
        while not stop.is_set():
            labels = kube.get_node("copycat")["metadata"]["labels"]
            want = labels.get(L.CC_MODE_LABEL)
            if want and labels.get(L.CC_MODE_STATE_LABEL) != want:
                kube.set_node_labels(
                    "copycat", {L.CC_MODE_STATE_LABEL: want})
            time.sleep(0.02)

    t = threading.Thread(target=agent, daemon=True)
    t.start()
    try:
        report = Rollout(kube, "on", group_timeout_s=1.5,
                         poll_s=0.05).run()
    finally:
        stop.set()
    (group,) = report.groups
    assert group.outcome == "timeout"
    assert "identity" in group.detail


def test_cached_token_survives_fetch_blip(monkeypatch):
    """A refresh blip inside the margin serves the still-valid cached
    token instead of stripping identity; expired cache + dead fetch
    raises."""
    calls = {"n": 0}

    class Flaky(FakePlatformIdentity):
        def token(self, node_name, audience=None):
            calls["n"] += 1
            if calls["n"] > 1:
                raise OSError("metadata blip")
            return mint_fake_token(node_name, KEY, ttl_s=10)

    p = Flaky(KEY)
    tok1 = p.cached_token("n1")
    # push past the refresh margin but not past expiry: fetch fails,
    # the cached token is served
    p._cache[("n1", "tpu-cc-manager")] = (
        tok1, time.time() - 9, time.time() + 1)
    assert p.cached_token("n1") == tok1
    # past expiry: the blip propagates
    p._cache[("n1", "tpu-cc-manager")] = (
        tok1, time.time() - 20, time.time() - 1)
    with pytest.raises(OSError):
        p.cached_token("n1")


def test_identity_fetch_blip_retried_from_idle_tick(tmp_path,
                                                    monkeypatch):
    """A metadata blip during a publish must not strip identity for
    the process lifetime: the agent schedules a retry deadline even
    though the published doc carries no token."""
    from tpu_cc_manager.agent import CCManagerAgent
    from tpu_cc_manager.config import AgentConfig

    monkeypatch.setenv("TPU_CC_IDENTITY", "fake")
    # no identity key: the fake provider's token() raises -> the doc
    # publishes identity-less, exactly like a metadata outage
    monkeypatch.delenv("TPU_CC_IDENTITY_KEY", raising=False)
    be = _backend(tmp_path, monkeypatch)
    kube = FakeKube()
    kube.add_node(make_node("blip-node"))
    cfg = AgentConfig(node_name="blip-node", drain_strategy="none",
                      health_port=0, emit_events=False)
    agent = CCManagerAgent(kube, cfg, backend=be)
    assert agent.reconcile("on") is True
    assert agent.flush_events(timeout=10)
    doc = json.loads(kube.get_node("blip-node")["metadata"]
                     ["annotations"][L.EVIDENCE_ANNOTATION])
    assert "identity" not in doc
    # ...but a retry is scheduled, because a provider IS configured
    assert agent._evidence_identity_refresh_at is not None

    # the 'metadata server' recovers; the due idle tick attaches
    monkeypatch.setenv("TPU_CC_IDENTITY_KEY", KEY.decode())
    agent._evidence_identity_refresh_at = time.time() - 1
    agent._evidence_key_check_due = 0.0
    agent._maybe_repair()
    assert agent.flush_events(timeout=10)
    doc = json.loads(kube.get_node("blip-node")["metadata"]
                     ["annotations"][L.EVIDENCE_ANNOTATION])
    assert judge_identity(doc, "blip-node", key=KEY) == ("ok", "ok")


# --------------------------------------------------------------- RS256
@pytest.fixture(scope="module")
def rsa_key(tmp_path_factory):
    """Real RSA keypair via the openssl CLI (stdlib can't generate
    RSA); returns (private_pem_path, jwks_dict with kid 'test-kid')."""
    import base64
    import shutil
    import subprocess

    if shutil.which("openssl") is None:
        pytest.skip("openssl binary unavailable")
    d = tmp_path_factory.mktemp("rsa")
    key = d / "key.pem"
    r = subprocess.run(["openssl", "genrsa", "-out", str(key), "2048"],
                       capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip(f"openssl genrsa unavailable: {r.stderr}")
    mod = subprocess.run(
        ["openssl", "rsa", "-in", str(key), "-noout", "-modulus"],
        capture_output=True, text=True, check=True,
    ).stdout.strip()
    n = bytes.fromhex(mod.split("=", 1)[1])

    def b64url(b):
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    jwks = {"keys": [{
        "kty": "RSA", "kid": "test-kid", "alg": "RS256", "use": "sig",
        "n": b64url(n), "e": b64url((65537).to_bytes(3, "big")),
    }]}
    return str(key), jwks


def _mint_rs256(key_path, node, audience=None, now=None, kid="test-kid"):
    """RS256 JWT shaped like a real GCE full-format token, signed with
    the test key through the openssl CLI (an implementation that shares
    NOTHING with the verifier under test)."""
    import base64
    import subprocess
    import tempfile

    def b64url(b):
        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    now = time.time() if now is None else now
    header = {"alg": "RS256", "typ": "JWT", "kid": kid}
    payload = {
        "iss": "https://accounts.google.com",
        "aud": audience or "tpu-cc-manager",
        "iat": int(now), "exp": int(now + 3600),
        "google": {"compute_engine": {"instance_name": node}},
    }
    signing_input = (
        b64url(json.dumps(header, sort_keys=True).encode()) + "." +
        b64url(json.dumps(payload, sort_keys=True).encode())
    )
    with tempfile.NamedTemporaryFile(suffix=".bin") as f:
        f.write(signing_input.encode())
        f.flush()
        sig = subprocess.run(
            ["openssl", "dgst", "-sha256", "-sign", key_path, f.name],
            capture_output=True, check=True,
        ).stdout
    return signing_input + "." + b64url(sig)


def test_rs256_verified_against_provisioned_jwks(rsa_key, tmp_path,
                                                 monkeypatch):
    """With a provisioned JWKS (the Google certs document, mounted as
    a ConfigMap in production) a real RS256 GCE token verifies FULLY
    offline — no more 'unverifiable' blind spot."""
    key_path, jwks = rsa_key
    jwks_file = tmp_path / "jwks.json"
    jwks_file.write_text(json.dumps(jwks))
    monkeypatch.setenv("TPU_CC_IDENTITY_JWKS_FILE", str(jwks_file))

    tok = _mint_rs256(key_path, "gke-node-1")
    assert verify_token(tok, node_name="gke-node-1") == ("ok", "ok")
    # node binding still outranks the signature
    assert verify_token(tok, node_name="other")[0] == "mismatch"
    # expired-but-valid classes as staleness
    old = _mint_rs256(key_path, "gke-node-1", now=time.time() - 7200)
    assert verify_token(old, node_name="gke-node-1")[0] == "expired"


def test_rs256_forgeries_rejected_with_jwks(rsa_key, tmp_path,
                                            monkeypatch):
    key_path, jwks = rsa_key
    jwks_file = tmp_path / "jwks.json"
    jwks_file.write_text(json.dumps(jwks))
    monkeypatch.setenv("TPU_CC_IDENTITY_JWKS_FILE", str(jwks_file))

    tok = _mint_rs256(key_path, "gke-node-1")
    head, payload, sig = tok.split(".")
    # payload swapped under the same signature: invalid
    other = _mint_rs256(key_path, "victim")
    spliced = ".".join([head, other.split(".")[1], sig])
    verdict, detail = verify_token(spliced, node_name="victim")
    assert verdict == "invalid" and "signature" in detail
    # unknown kid: NOT forgery — Google rotates keys and the mounted
    # JWKS can lag; a stale verifier artifact is a blind spot, not an
    # attack, so the fleet must not page as identity_mismatch
    rogue = _mint_rs256(key_path, "gke-node-1", kid="unknown-kid")
    verdict, detail = verify_token(rogue, node_name="gke-node-1")
    assert verdict == "unverifiable" and "kid" in detail
    # garbage signature bytes: invalid
    bad = ".".join([head, payload, "AAAA"])
    assert verify_token(bad, node_name="gke-node-1")[0] == "invalid"


def test_rs256_without_jwks_still_degrades_honestly(rsa_key,
                                                    monkeypatch):
    key_path, _ = rsa_key
    monkeypatch.delenv("TPU_CC_IDENTITY_JWKS_FILE", raising=False)
    tok = _mint_rs256(key_path, "gke-node-1")
    assert verify_token(tok, node_name="gke-node-1")[0] == "unverifiable"
