"""ccaudit v6 — the resource & overload-discipline families.

Positive/negative/pragma coverage per family (unbounded-queue,
missing-deadline, retry-discipline, resource-leak, stop-aware-wait),
plus the cross-cutting pins: the caller-path ⋂-fixpoint for forwarded
deadline parameters, the live tree passing its own v6 rules, SARIF
severity mapping, ``--files`` slice soundness, and the fact cache.
"""

import os

import pytest

from tpu_cc_manager.analysis import RULES
from tpu_cc_manager.analysis.core import (
    CACHE_DIR_NAME,
    analyze_paths,
    analyze_source,
    analyzer_version_hash,
    load_audit_cached,
)
from tpu_cc_manager.analysis.resourceflow import (
    DEADLINE_RULE,
    LEAK_RULE,
    QUEUE_RULE,
    RESOURCEFLOW_RULES,
    RETRY_RULE,
    STOP_RULE,
)
from tpu_cc_manager.analysis.sarif import to_sarif, validate_sarif

#: a non-exempt module OUTSIDE the stop surface and the I/O core
MOD = "tpu_cc_manager/misc.py"
#: a stop-surface controller module (fixed frozenset in resourceflow)
STOP_MOD = "tpu_cc_manager/fleet.py"
#: an I/O-core module — every function there roots the deadline closure
IO_MOD = "tpu_cc_manager/k8s/aio.py"


def _hits(src, rule, relpath=MOD):
    return [f for f in analyze_source(src, relpath) if f.rule == rule]


# ------------------------------------------------------- rule registry


def test_v6_families_registered():
    assert RESOURCEFLOW_RULES == (
        QUEUE_RULE, DEADLINE_RULE, RETRY_RULE, LEAK_RULE, STOP_RULE,
    )
    for rule in RESOURCEFLOW_RULES:
        assert rule in RULES


# --------------------------------------------------- unbounded-queue


def test_module_level_queue_without_maxsize_flagged():
    src = (
        "import queue\n"
        "BACKLOG = queue.Queue()\n"
    )
    hits = _hits(src, QUEUE_RULE)
    assert len(hits) == 1
    assert hits[0].line == 2
    assert hits[0].severity == "error"


def test_bounded_queue_passes():
    src = (
        "import queue\n"
        "BACKLOG = queue.Queue(maxsize=64)\n"
    )
    assert _hits(src, QUEUE_RULE) == []


def test_maxsize_zero_means_unbounded():
    # queue.Queue(0) is the stdlib's "infinite" spelling — still a
    # backlog with no bound
    src = (
        "import queue\n"
        "BACKLOG = queue.Queue(0)\n"
    )
    assert len(_hits(src, QUEUE_RULE)) == 1


def test_asyncio_queue_on_self_flagged():
    src = (
        "import asyncio\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self._q = asyncio.Queue()\n"
    )
    hits = _hits(src, QUEUE_RULE)
    assert len(hits) == 1
    assert hits[0].line == 4


def test_simplequeue_never_boundable():
    src = (
        "import queue\n"
        "EVENTS = queue.SimpleQueue()\n"
    )
    hits = _hits(src, QUEUE_RULE)
    assert len(hits) == 1
    assert "no bound at all" in hits[0].message


def test_local_scratch_deque_exempt_but_self_deque_flagged():
    # a function-local deque is a scratch working set; one stored on
    # self crosses contexts and is a real backlog
    src = (
        "from collections import deque\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._ready = deque()\n"
        "    def scan(self, items):\n"
        "        work = deque()\n"
        "        work.extend(items)\n"
    )
    hits = _hits(src, QUEUE_RULE)
    assert [f.line for f in hits] == [4]


def test_deque_maxlen_none_is_no_bound_but_positional_bound_is():
    src = (
        "from collections import deque\n"
        "A = deque(maxlen=None)\n"
        "B = deque([], 128)\n"
        "C = deque(maxlen=128)\n"
    )
    assert [f.line for f in _hits(src, QUEUE_RULE)] == [2]


def test_queue_pragma_suppresses():
    src = (
        "import queue\n"
        "# ccaudit: allow-unbounded-queue(drained every tick by design)\n"
        "BACKLOG = queue.Queue()\n"
    )
    assert _hits(src, QUEUE_RULE) == []


def test_queue_exempt_prefixes_pass():
    src = (
        "import queue\n"
        "BACKLOG = queue.Queue()\n"
    )
    assert _hits(src, QUEUE_RULE, relpath="scripts/oneshot.py") == []
    assert _hits(src, QUEUE_RULE,
                 relpath="tpu_cc_manager/simlab/run.py") == []


# --------------------------------------------------- stop-aware-wait


def test_sleep_in_controller_loop_is_error():
    src = (
        "import time\n"
        "class F:\n"
        "    def run(self):\n"
        "        while True:\n"
        "            time.sleep(5)\n"
    )
    hits = _hits(src, STOP_RULE, relpath=STOP_MOD)
    assert len(hits) == 1
    assert hits[0].severity == "error"


def test_one_shot_sleep_is_warning():
    src = (
        "import time\n"
        "def settle():\n"
        "    time.sleep(0.5)\n"
    )
    hits = _hits(src, STOP_RULE, relpath=STOP_MOD)
    assert len(hits) == 1
    assert hits[0].severity == "warning"


def test_stop_event_wait_is_the_convention():
    src = (
        "class F:\n"
        "    def run(self):\n"
        "        while not self._stop.is_set():\n"
        "            self._stop.wait(5.0)\n"
    )
    assert _hits(src, STOP_RULE, relpath=STOP_MOD) == []


def test_untimed_event_wait_flagged():
    src = (
        "class F:\n"
        "    def run(self, ready):\n"
        "        ready.wait()\n"
    )
    hits = _hits(src, STOP_RULE, relpath=STOP_MOD)
    assert len(hits) == 1
    assert "no timeout" in hits[0].message


def test_timed_wait_in_stop_checking_loop_passes():
    src = (
        "class F:\n"
        "    def run(self, ready):\n"
        "        while not self._stop.is_set():\n"
        "            ready.wait(1.0)\n"
    )
    assert _hits(src, STOP_RULE, relpath=STOP_MOD) == []


def test_timed_wait_in_blind_loop_is_error():
    src = (
        "class F:\n"
        "    def run(self, ready):\n"
        "        while True:\n"
        "            ready.wait(1.0)\n"
    )
    hits = _hits(src, STOP_RULE, relpath=STOP_MOD)
    assert len(hits) == 1
    assert hits[0].severity == "error"
    assert "without consulting the stop signal" in hits[0].message


def test_deadline_clamped_wait_in_blind_loop_passes():
    # waiting out `remaining` is bounded overall even when the loop
    # test is blind — the deadline, not the stop event, ends it
    src = (
        "import time\n"
        "class F:\n"
        "    def join(self, ready, deadline):\n"
        "        while True:\n"
        "            remaining = deadline - time.monotonic()\n"
        "            ready.wait(remaining)\n"
    )
    assert _hits(src, STOP_RULE, relpath=STOP_MOD) == []


def test_blocking_queue_get_flagged_and_timeout_passes():
    src = (
        "class F:\n"
        "    def pump(self, queue):\n"
        "        item = queue.get()\n"
        "    def pump2(self, queue):\n"
        "        item = queue.get(timeout=1.0)\n"
    )
    hits = _hits(src, STOP_RULE, relpath=STOP_MOD)
    assert [f.line for f in hits] == [3]


def test_stop_rule_only_on_surface_modules():
    src = (
        "import time\n"
        "def run():\n"
        "    while True:\n"
        "        time.sleep(5)\n"
    )
    assert _hits(src, STOP_RULE, relpath=MOD) == []


def test_stop_pragma_suppresses():
    src = (
        "import time\n"
        "def capture():\n"
        "    # ccaudit: allow-stop-aware-wait(bounded burst, <=2s)\n"
        "    time.sleep(2.0)\n"
    )
    assert _hits(src, STOP_RULE, relpath=STOP_MOD) == []


# ----------------------------------------------------- resource-leak


def test_never_released_socket_flagged():
    src = (
        "import socket\n"
        "def probe(host):\n"
        "    s = socket.socket()\n"
        "    s.connect((host, 80))\n"
    )
    hits = _hits(src, LEAK_RULE)
    assert len(hits) == 1
    assert "never" in hits[0].message


def test_success_only_close_flagged():
    src = (
        "def dump(path):\n"
        "    f = open(path)\n"
        "    f.seek(0)\n"
        "    f.close()\n"
    )
    hits = _hits(src, LEAK_RULE)
    assert len(hits) == 1
    assert "straight-line" in hits[0].message


def test_close_in_finally_passes():
    src = (
        "def dump(path):\n"
        "    f = open(path)\n"
        "    try:\n"
        "        f.seek(0)\n"
        "    finally:\n"
        "        f.close()\n"
    )
    assert _hits(src, LEAK_RULE) == []


def test_with_statement_on_handle_passes():
    src = (
        "import socket\n"
        "def probe(host):\n"
        "    s = socket.socket()\n"
        "    with s:\n"
        "        s.connect((host, 80))\n"
    )
    assert _hits(src, LEAK_RULE) == []


def test_returned_handle_is_a_transfer():
    src = (
        "import socket\n"
        "def dial(host):\n"
        "    s = socket.socket()\n"
        "    return s\n"
    )
    assert _hits(src, LEAK_RULE) == []


def test_self_attr_acquire_without_module_close_flagged():
    src = (
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class E:\n"
        "    def start(self):\n"
        "        self._pool = ThreadPoolExecutor(max_workers=4)\n"
    )
    hits = _hits(src, LEAK_RULE)
    assert len(hits) == 1
    assert "self._pool" in hits[0].message


def test_self_attr_with_close_elsewhere_passes():
    src = (
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class E:\n"
        "    def start(self):\n"
        "        self._pool = ThreadPoolExecutor(max_workers=4)\n"
        "    def stop(self):\n"
        "        self._pool.shutdown(wait=False)\n"
    )
    assert _hits(src, LEAK_RULE) == []


def test_swap_out_then_shutdown_idiom_passes():
    # `pool, self._pool = self._pool, None` visibly hands the handle to
    # managing code — the engine.py release idiom
    src = (
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class E:\n"
        "    def start(self):\n"
        "        self._pool = ThreadPoolExecutor(max_workers=4)\n"
        "    def stop(self):\n"
        "        pool, self._pool = self._pool, None\n"
        "        pool.shutdown(wait=False)\n"
    )
    assert _hits(src, LEAK_RULE) == []


def test_leak_pragma_suppresses():
    src = (
        "import socket\n"
        "def probe(host):\n"
        "    # ccaudit: allow-resource-leak(process-lifetime handle)\n"
        "    s = socket.socket()\n"
        "    s.connect((host, 80))\n"
    )
    assert _hits(src, LEAK_RULE) == []


# ------------------------------------------------- retry-discipline


def test_naked_while_true_retry_missing_all_three_legs():
    src = (
        "import time\n"
        "def push(kube):\n"
        "    while True:\n"
        "        try:\n"
        "            kube.patch_node('a', {})\n"
        "            return\n"
        "        except Exception:\n"
        "            time.sleep(1)\n"
    )
    hits = _hits(src, RETRY_RULE)
    assert len(hits) == 1
    assert hits[0].severity == "warning"
    for leg in ("cap", "backoff growth", "jitter"):
        assert leg in hits[0].message


def test_capped_jittered_backoff_loop_passes():
    src = (
        "import random\n"
        "import time\n"
        "def push(kube):\n"
        "    delay = 0.1\n"
        "    for attempt in range(5):\n"
        "        try:\n"
        "            kube.patch_node('a', {})\n"
        "            return\n"
        "        except Exception:\n"
        "            time.sleep(delay * random.random())\n"
        "            delay = delay * 2\n"
    )
    assert _hits(src, RETRY_RULE) == []


def test_missing_jitter_named_specifically():
    src = (
        "import time\n"
        "def push(kube):\n"
        "    delay = 0.1\n"
        "    for attempt in range(5):\n"
        "        try:\n"
        "            kube.patch_node('a', {})\n"
        "            return\n"
        "        except Exception:\n"
        "            time.sleep(delay)\n"
        "            delay = delay * 2\n"
    )
    hits = _hits(src, RETRY_RULE)
    assert len(hits) == 1
    assert "is missing jitter:" in hits[0].message


def test_for_over_collection_is_a_scan_not_a_retry():
    # `except: continue` in a per-item loop skips the item; it never
    # re-attempts the same work, so retry discipline does not apply
    src = (
        "def sweep(kube, nodes):\n"
        "    for n in nodes:\n"
        "        try:\n"
        "            kube.patch_node(n, {})\n"
        "        except Exception:\n"
        "            continue\n"
    )
    assert _hits(src, RETRY_RULE) == []


def test_two_attempt_replay_loop_exempt():
    src = (
        "def flush(sock):\n"
        "    for attempt in (0, 1):\n"
        "        try:\n"
        "            sock.send(b'x')\n"
        "            return\n"
        "        except OSError:\n"
        "            sock = reconnect()\n"
    )
    assert _hits(src, RETRY_RULE) == []


def test_transitive_backoff_helper_satisfies_the_legs():
    # the loop itself shows no growth or randomness — both legs live in
    # the called helper, found through the call-graph closure
    src = (
        "import random\n"
        "def jittered_backoff(base, attempt):\n"
        "    return min(60.0, base * 2 ** attempt) * random.random()\n"
        "def watch(kube, stop):\n"
        "    failures = 0\n"
        "    while not stop.is_set():\n"
        "        try:\n"
        "            kube.list_nodes()\n"
        "        except Exception:\n"
        "            failures = failures + 1\n"
        "            stop.wait(jittered_backoff(0.2, failures))\n"
    )
    assert _hits(src, RETRY_RULE) == []


def test_handler_ending_in_raise_is_not_a_retry():
    src = (
        "def push(kube):\n"
        "    while True:\n"
        "        try:\n"
        "            kube.patch_node('a', {})\n"
        "            return\n"
        "        except Exception:\n"
        "            raise\n"
    )
    assert _hits(src, RETRY_RULE) == []


def test_retry_pragma_suppresses():
    src = (
        "import time\n"
        "def push(kube):\n"
        "    # ccaudit: allow-retry-discipline(supersession follow-up)\n"
        "    while True:\n"
        "        try:\n"
        "            kube.patch_node('a', {})\n"
        "            return\n"
        "        except Exception:\n"
        "            time.sleep(1)\n"
    )
    assert _hits(src, RETRY_RULE) == []


# ------------------------------------------------- missing-deadline


def test_bare_awaited_readline_in_io_core_flagged():
    src = (
        "async def head(reader):\n"
        "    return await reader.readline()\n"
    )
    hits = _hits(src, DEADLINE_RULE, relpath=IO_MOD)
    assert len(hits) == 1
    assert "reader.readline()" in hits[0].message


def test_wait_for_wrapped_read_passes():
    src = (
        "import asyncio\n"
        "async def head(reader):\n"
        "    return await asyncio.wait_for(reader.readline(), 5.0)\n"
    )
    assert _hits(src, DEADLINE_RULE, relpath=IO_MOD) == []


def test_wait_for_with_none_timeout_flagged():
    src = (
        "import asyncio\n"
        "async def head(reader):\n"
        "    return await asyncio.wait_for(reader.readline(), None)\n"
    )
    assert len(_hits(src, DEADLINE_RULE, relpath=IO_MOD)) == 1


def test_deadline_clamp_expression_is_bounded():
    src = (
        "import asyncio\n"
        "import time\n"
        "async def head(reader, deadline):\n"
        "    t = min(5.0, deadline - time.monotonic())\n"
        "    return await asyncio.wait_for(reader.readline(), t)\n"
    )
    assert _hits(src, DEADLINE_RULE, relpath=IO_MOD) == []


def test_sync_sink_in_reconcile_root_flagged():
    # `reconcile` roots the closure by name in any non-exempt module
    src = (
        "import requests\n"
        "def reconcile(url):\n"
        "    return requests.get(url)\n"
    )
    hits = _hits(src, DEADLINE_RULE)
    assert len(hits) == 1
    assert "requests.get" in hits[0].message


def test_sync_sink_with_timeout_passes():
    src = (
        "import requests\n"
        "def reconcile(url):\n"
        "    return requests.get(url, timeout=5.0)\n"
    )
    assert _hits(src, DEADLINE_RULE) == []


def test_future_result_without_timeout_flagged_in_closure():
    src = (
        "def run_flips(futures):\n"
        "    return [f.result() for f in futures]\n"
    )
    assert len(_hits(src, DEADLINE_RULE)) == 1
    src_ok = (
        "def run_flips(futures):\n"
        "    return [f.result(30.0) for f in futures]\n"
    )
    assert _hits(src_ok, DEADLINE_RULE) == []


def test_sinks_outside_the_closure_pass():
    # not a root name, not I/O core, no path from a root: out of scope
    src = (
        "import requests\n"
        "def helper(url):\n"
        "    return requests.get(url)\n"
    )
    assert _hits(src, DEADLINE_RULE) == []


def test_stop_governed_await_wait_passes():
    src = (
        "class K:\n"
        "    async def pump(self):\n"
        "        await self._stop.wait()\n"
    )
    assert _hits(src, DEADLINE_RULE, relpath=IO_MOD) == []


def test_forwarded_param_unbounded_on_one_caller_path():
    # the ⋂-fixpoint pin: the sink's timeout rides `timeout_s`, and ONE
    # caller path passes an explicit None — the parameter is unbounded
    # and the finding names it
    src = (
        "import asyncio\n"
        "async def _round(reader, timeout_s):\n"
        "    return await asyncio.wait_for(reader.readline(), timeout_s)\n"
        "async def fast(reader):\n"
        "    return await _round(reader, 5.0)\n"
        "async def forever(reader):\n"
        "    return await _round(reader, None)\n"
    )
    hits = _hits(src, DEADLINE_RULE, relpath=IO_MOD)
    assert len(hits) == 1
    assert hits[0].line == 3
    assert "timeout_s" in hits[0].message


def test_forwarded_param_bounded_on_every_caller_path():
    src = (
        "import asyncio\n"
        "async def _round(reader, timeout_s):\n"
        "    return await asyncio.wait_for(reader.readline(), timeout_s)\n"
        "async def fast(reader):\n"
        "    return await _round(reader, 5.0)\n"
        "async def slow(reader):\n"
        "    return await _round(reader, 60.0)\n"
    )
    assert _hits(src, DEADLINE_RULE, relpath=IO_MOD) == []


def test_unbounded_default_with_bounded_caller_passes():
    # the caller supplies the bound, so the None default never binds
    src = (
        "import asyncio\n"
        "async def _round(reader, timeout_s=None):\n"
        "    return await asyncio.wait_for(reader.readline(), timeout_s)\n"
        "async def fast(reader):\n"
        "    return await _round(reader, 5.0)\n"
    )
    assert _hits(src, DEADLINE_RULE, relpath=IO_MOD) == []


def test_unbounded_default_rides_an_omitting_caller():
    # a caller that omits the argument contributes the None default to
    # the parameter's site set — that path is unbounded
    src = (
        "import asyncio\n"
        "async def _round(reader, timeout_s=None):\n"
        "    return await asyncio.wait_for(reader.readline(), timeout_s)\n"
        "async def fast(reader):\n"
        "    return await _round(reader)\n"
    )
    hits = _hits(src, DEADLINE_RULE, relpath=IO_MOD)
    assert len(hits) == 1
    assert "timeout_s" in hits[0].message


def test_deadline_pragma_suppresses():
    src = (
        "async def head(reader):\n"
        "    # ccaudit: allow-missing-deadline(owner task is cancelled)\n"
        "    return await reader.readline()\n"
    )
    assert _hits(src, DEADLINE_RULE, relpath=IO_MOD) == []


# ------------------------------------------------------------ SARIF


def test_sarif_levels_track_v6_severities():
    queue_hit = _hits(
        "import queue\nBACKLOG = queue.Queue()\n", QUEUE_RULE)
    leak_hit = _hits(
        "import socket\ndef probe(h):\n    s = socket.socket()\n"
        "    s.connect((h, 80))\n", LEAK_RULE)
    doc = to_sarif(queue_hit + leak_hit, [], [])
    assert validate_sarif(doc) == []
    levels = {r["ruleId"]: r["level"] for r in doc["runs"][0]["results"]}
    assert levels[QUEUE_RULE] == "error"
    assert levels[LEAK_RULE] == "warning"


# --------------------------------------------------------- fact cache


CACHED_SRC = (
    "import queue\n"
    "BACKLOG = queue.Queue()\n"
)


def _tree(tmp_path):
    pkg = tmp_path / "tpu_cc_manager"
    pkg.mkdir()
    (pkg / "m.py").write_text(CACHED_SRC)
    return pkg


def test_cached_scan_reports_exactly_the_cold_scan(tmp_path):
    _tree(tmp_path)
    target = ["tpu_cc_manager/m.py"]
    cold = analyze_paths(root=str(tmp_path), targets=target, cache=True)
    assert os.path.isdir(tmp_path / CACHE_DIR_NAME)
    warm = analyze_paths(root=str(tmp_path), targets=target, cache=True)
    assert cold == warm
    assert any(f.rule == QUEUE_RULE for f in warm)


def test_cache_content_change_reflects_in_v6_report(tmp_path):
    pkg = _tree(tmp_path)
    target = ["tpu_cc_manager/m.py"]
    cold = analyze_paths(root=str(tmp_path), targets=target, cache=True)
    assert any(f.rule == QUEUE_RULE for f in cold)
    (pkg / "m.py").write_text(
        "import queue\nBACKLOG = queue.Queue(maxsize=64)\n")
    warm = analyze_paths(root=str(tmp_path), targets=target, cache=True)
    assert not any(f.rule == QUEUE_RULE for f in warm)


def test_cache_round_trip_preserves_module_facts(tmp_path):
    _tree(tmp_path)
    cache = tmp_path / CACHE_DIR_NAME
    cache.mkdir()
    v = analyzer_version_hash()
    rel = "tpu_cc_manager/m.py"
    a1 = load_audit_cached(str(tmp_path), rel, str(cache), v)
    a2 = load_audit_cached(str(tmp_path), rel, str(cache), v)
    assert a2.module.relpath == rel
    assert a1.module.source == a2.module.source
    # v6 runs over the cached facts: same findings either way
    assert len(list(cache.iterdir())) == 1


# -------------------------------------------- live surface + slicing


@pytest.fixture(scope="module")
def full_scan():
    return analyze_paths()


def test_live_tree_passes_v6_clean(full_scan):
    # the shipped tree passes its own resource rules: the aio writer
    # backlog is bounded (TPU_CC_KUBE_QUEUE), every retry loop carries
    # cap+backoff+jitter or a pragma, and nothing new rides the
    # baseline (the ratchet only burns down)
    assert [f for f in full_scan if f.rule in RESOURCEFLOW_RULES] == []


def test_files_subset_reports_exactly_the_full_runs_slice(full_scan):
    # --files runs the ANALYSIS whole-program and slices only the
    # REPORT, so v6 facts (the deadline closure, the ⋂-fixpoint over
    # caller paths) never degrade on a changed-files pass
    target = "tpu_cc_manager/k8s/aio.py"
    sub = analyze_paths(targets=[target], subset=True)
    assert sorted(sub) == sorted(
        f for f in full_scan if f.file == target
    )
