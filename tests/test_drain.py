"""L2 drain tests: pause-label protocol, pod-wait, restore invariants, and
the GKE cordon/evict variant with PDB blocking."""

import threading
import time

from tpu_cc_manager import labels as L
from tpu_cc_manager.drain import (
    ComponentDrainer,
    NodeDrainer,
    paused_value,
    set_cc_mode_state_label,
    unpaused_value,
)
from tpu_cc_manager.k8s import FakeKube
from tpu_cc_manager.k8s.objects import make_node, make_pod

DP = "tpu.google.com/pool.deploy.device-plugin"
ME = "tpu.google.com/pool.deploy.metrics-exporter"


def _node_with_components(kube, name="n1", components=(DP, ME)):
    kube.add_node(make_node(name, labels={c: "true" for c in components}))


def test_paused_value_roundtrip():
    assert paused_value("true") == f"{L.PAUSED_STR}_true"
    assert unpaused_value(paused_value("true")) == "true"
    assert unpaused_value("true") == "true"  # idempotent


def test_state_label_writer():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    set_cc_mode_state_label(kube, "n1", "on")
    assert kube.get_node("n1")["metadata"]["labels"][L.CC_MODE_STATE_LABEL] == "on"


def test_evict_pauses_only_deployed_components():
    kube = FakeKube()
    _node_with_components(kube, components=(DP,))
    d = ComponentDrainer(kube, "n1", timeout_s=1, poll_s=0.05)
    d.evict()
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[DP] == paused_value("true")
    assert ME not in labels  # absent components untouched


def test_evict_waits_for_pods_to_leave():
    kube = FakeKube()
    _node_with_components(kube, components=(DP,))
    kube.add_pod(
        make_pod("dp-pod", "tpu-system",
                 labels={"app": L.COMPONENT_APP_LABELS[DP]}, node_name="n1")
    )
    d = ComponentDrainer(kube, "n1", timeout_s=5, poll_s=0.05)

    def delete_later():
        time.sleep(0.3)
        kube.delete_pod("tpu-system", "dp-pod")

    t = threading.Thread(target=delete_later)
    t.start()
    start = time.monotonic()
    d.evict()
    t.join()
    assert 0.2 <= time.monotonic() - start < 5


def test_evict_timeout_warns_and_continues():
    # timeout is warn-and-continue, not fatal (gpu_operator_eviction.py:205-207)
    kube = FakeKube()
    _node_with_components(kube, components=(DP,))
    kube.add_pod(
        make_pod("dp-pod", "tpu-system",
                 labels={"app": L.COMPONENT_APP_LABELS[DP]}, node_name="n1")
    )
    d = ComponentDrainer(kube, "n1", timeout_s=0.2, poll_s=0.05)
    d.evict()  # must return despite the pod never leaving


def test_reschedule_restores_original_values():
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={DP: "true", ME: "enabled"}))
    d = ComponentDrainer(kube, "n1", timeout_s=0.1, poll_s=0.05)
    d.evict()
    d.reschedule()
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[DP] == "true"
    assert labels[ME] == "enabled"


def test_reschedule_after_agent_restart_uses_live_state():
    # durable state lives in the labels: a fresh drainer (crashed agent)
    # can still unpause (SURVEY.md §5.4)
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={DP: paused_value("true")}))
    d = ComponentDrainer(kube, "n1")
    d.reschedule()
    assert kube.get_node("n1")["metadata"]["labels"][DP] == "true"


def test_evict_skips_false_and_already_paused():
    kube = FakeKube()
    kube.add_node(make_node("n1", labels={DP: "false", ME: paused_value("true")}))
    d = ComponentDrainer(kube, "n1", timeout_s=0.1, poll_s=0.05)
    d.evict()
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[DP] == "false"  # disabled component never paused
    assert labels[ME] == paused_value("true")  # not double-paused


# ------------------------------------------------------------- NodeDrainer
def test_node_drainer_cordons_evicts_uncordons():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    kube.add_pod(make_pod("w1", "default", labels={"tpu": "yes"}, node_name="n1"))
    kube.add_pod(make_pod("w2", "default", labels={"tpu": "yes"}, node_name="other"))
    d = NodeDrainer(kube, "n1", timeout_s=2, poll_s=0.05)
    d.evict()
    assert kube.get_node("n1")["spec"]["unschedulable"] is True
    names = [p["metadata"]["name"] for p in kube.list_pods("default")]
    assert names == ["w2"]  # only n1's pods evicted
    d.reschedule()
    assert kube.get_node("n1")["spec"]["unschedulable"] is False


def test_node_drainer_retries_pdb_blocked_until_timeout():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    kube.add_pod(make_pod("w1", "default", node_name="n1"))
    kube.pdb_blocked.add(("default", "w1"))

    def unblock_later():
        time.sleep(0.3)
        kube.pdb_blocked.clear()

    t = threading.Thread(target=unblock_later)
    t.start()
    d = NodeDrainer(kube, "n1", timeout_s=5, poll_s=0.05)
    d.evict()
    t.join()
    assert kube.list_pods("default") == []


def test_node_drainer_pdb_timeout_warns_and_continues():
    kube = FakeKube()
    kube.add_node(make_node("n1"))
    kube.add_pod(make_pod("w1", "default", node_name="n1"))
    kube.pdb_blocked.add(("default", "w1"))
    d = NodeDrainer(kube, "n1", timeout_s=0.2, poll_s=0.05)
    d.evict()  # returns despite the PDB never unblocking
    assert len(kube.list_pods("default")) == 1


def test_drain_wait_wakes_on_watch_event():
    """ISSUE 14's wake treatment: with a wake event wired, the pod-wait
    re-checks the moment the event pulses (the agent fires it from its
    node-watch delta thread) instead of sleeping out a full poll — here
    poll_s is 5s and the drain still finishes in well under one tick."""
    kube = FakeKube()
    _node_with_components(kube, components=(DP,))
    kube.add_pod(
        make_pod("dp-pod", "tpu-system",
                 labels={"app": L.COMPONENT_APP_LABELS[DP]}, node_name="n1")
    )
    wake = threading.Event()
    d = ComponentDrainer(kube, "n1", timeout_s=20, poll_s=5.0, wake=wake)

    def delete_and_pulse():
        time.sleep(0.2)
        kube.delete_pod("tpu-system", "dp-pod")
        wake.set()  # the watch delta the agent would deliver

    t = threading.Thread(target=delete_and_pulse)
    t.start()
    start = time.monotonic()
    d.evict()
    t.join()
    elapsed = time.monotonic() - start
    assert 0.15 <= elapsed < 2.0, (
        f"drain took {elapsed:.2f}s — the wake did not cut the 5s poll"
    )


def test_drain_wait_without_wake_keeps_interval_poll():
    """A bare drainer (no wake source) keeps the historical poll: the
    liveness fallback still converges the wait, one poll tick late."""
    kube = FakeKube()
    _node_with_components(kube, components=(DP,))
    kube.add_pod(
        make_pod("dp-pod", "tpu-system",
                 labels={"app": L.COMPONENT_APP_LABELS[DP]}, node_name="n1")
    )
    d = ComponentDrainer(kube, "n1", timeout_s=5, poll_s=0.05)

    def delete_later():
        time.sleep(0.2)
        kube.delete_pod("tpu-system", "dp-pod")

    t = threading.Thread(target=delete_later)
    t.start()
    start = time.monotonic()
    d.evict()
    t.join()
    assert 0.2 <= time.monotonic() - start < 5


def test_build_drainer_threads_wake_through():
    from tpu_cc_manager.drain import NodeDrainer, build_drainer

    class Cfg:
        node_name = "n1"
        operator_namespace = "tpu-system"
        drain_strategy = "node"

    wake = threading.Event()
    d = build_drainer(FakeKube(), Cfg(), wake=wake)
    assert isinstance(d, NodeDrainer) and d.wake is wake
    Cfg.drain_strategy = "components"
    d = build_drainer(FakeKube(), Cfg(), wake=wake)
    assert isinstance(d, ComponentDrainer) and d.wake is wake
