import pytest

from tpu_cc_manager.modes import (
    CC_MODES,
    Mode,
    InvalidModeError,
    parse_mode,
)


def test_parse_valid_modes():
    assert parse_mode("on") is Mode.ON
    assert parse_mode("off") is Mode.OFF
    assert parse_mode("devtools") is Mode.DEVTOOLS
    assert parse_mode("ici") is Mode.ICI


@pytest.mark.parametrize("bad", ["", "ON", "enabled", "ppcie", "true"])
def test_parse_invalid_modes_loud(bad):
    # invalid values are rejected, never defaulted (reference main.py:144-158)
    with pytest.raises(InvalidModeError):
        parse_mode(bad)


def test_cc_modes_exclude_ici():
    assert Mode.ICI not in CC_MODES
    assert set(CC_MODES) == {Mode.ON, Mode.OFF, Mode.DEVTOOLS}
