import pytest

from tpu_cc_manager.modes import (
    CC_MODES,
    Mode,
    InvalidModeError,
    parse_mode,
)


def test_parse_valid_modes():
    assert parse_mode("on") is Mode.ON
    assert parse_mode("off") is Mode.OFF
    assert parse_mode("devtools") is Mode.DEVTOOLS
    assert parse_mode("ici") is Mode.ICI


@pytest.mark.parametrize("bad", ["", "ON", "enabled", "ppcie", "true"])
def test_parse_invalid_modes_loud(bad):
    # invalid values are rejected, never defaulted (reference main.py:144-158)
    with pytest.raises(InvalidModeError):
        parse_mode(bad)


def test_cc_modes_exclude_ici():
    assert Mode.ICI not in CC_MODES
    assert set(CC_MODES) == {Mode.ON, Mode.OFF, Mode.DEVTOOLS}


def test_oneshot_cli_posts_reconcile_event(tmp_path, monkeypatch):
    """The one-shot set-cc-mode CLI has the same Event visibility as the
    agent and the bash engine."""
    import os
    import subprocess
    import sys

    from tpu_cc_manager.k8s.apiserver import FakeApiServer
    from tpu_cc_manager.k8s.objects import make_node
    import yaml

    sysfs = tmp_path / "sysfs" / "accel0" / "device"
    sysfs.mkdir(parents=True)
    (sysfs / "vendor").write_text("0x1ae0\n")
    (sysfs / "device").write_text("0x0063\n")
    dev = tmp_path / "dev"
    dev.mkdir()
    (dev / "accel0").write_text("")

    with FakeApiServer() as srv:
        srv.store.add_node(make_node("cli-node"))
        kc = tmp_path / "kubeconfig.yaml"
        kc.write_text(yaml.safe_dump({
            "apiVersion": "v1", "kind": "Config", "current-context": "c",
            "contexts": [{"name": "c",
                          "context": {"cluster": "l", "user": "u"}}],
            "clusters": [{"name": "l", "cluster": {
                "server": f"http://127.0.0.1:{srv.port}"}}],
            "users": [{"name": "u", "user": {}}],
        }))
        env = dict(os.environ)
        env.update(
            NODE_NAME="cli-node", KUBECONFIG=str(kc),
            PYTHONPATH=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            TPU_SYSFS_ROOT=str(tmp_path / "sysfs"),
            TPU_DEV_ROOT=str(dev),
            TPU_CC_STATE_DIR=str(tmp_path / "state"),
            DRAIN_STRATEGY="none",
        )
        r = subprocess.run(
            [sys.executable, "-m", "tpu_cc_manager", "set-cc-mode",
             "-m", "devtools"],
            capture_output=True, text=True, env=env, timeout=60,
        )
        assert r.returncode == 0, r.stderr
        evs = srv.store.list_events("default")
        assert [e["reason"] for e in evs] == ["CCModeApplied"]
        assert ".cc-oneshot." in evs[0]["metadata"]["name"]
