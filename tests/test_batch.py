"""NodePatchBatcher — the write-coalescing I/O layer (ISSUE 6).

Pins the coalescing contract docs/io.md states: newest generation wins
(superseded publications are counted, not silent), carrier folds retire
exactly the generations they transported, the fail-secure ordered write
is one atomic merge patch that drains the queue for free and leaves
nothing half-applied on failure, and the bounded retry/backoff path
accounts every retry and drop.
"""

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.batch import NodePatchBatcher
from tpu_cc_manager.k8s.client import ApiException
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node


@pytest.fixture()
def kube():
    k = FakeKube()
    k.add_node(make_node("n1"))
    return k


def test_defer_coalesces_to_newest_generation(kube):
    seen = []
    b = NodePatchBatcher(kube, "n1", on_coalesced=seen.append)
    b.defer("evidence", annotations={L.EVIDENCE_ANNOTATION: "v1"})
    b.defer("evidence", annotations={L.EVIDENCE_ANNOTATION: "v2"})
    b.defer("evidence", annotations={L.EVIDENCE_ANNOTATION: "v3"})
    assert b.stats()["coalesced"] == 2
    assert seen == ["evidence", "evidence"]
    assert b.flush() is True
    ann = kube.get_node("n1")["metadata"]["annotations"]
    assert ann[L.EVIDENCE_ANNOTATION] == "v3"  # only the newest landed
    # exactly ONE write request carried it
    assert kube.node_write_stats()["requests"] == 1


def test_flush_fires_exact_generation_callbacks(kube):
    b = NodePatchBatcher(kube, "n1")
    published = []
    g1 = b.defer("evidence", annotations={"a": "1"},
                 on_published=published.append)
    g2 = b.defer("evidence", annotations={"a": "2"},
                 on_published=published.append)
    assert g2 > g1
    b.flush()
    # the superseded g1 never claims publication; g2 does, once
    assert published == [g2]


def test_fold_into_node_rides_a_cas_replace(kube):
    b = NodePatchBatcher(kube, "n1")
    b.defer("evidence", annotations={L.EVIDENCE_ANNOTATION: "ev"})
    b.defer("doctor", labels={L.DOCTOR_OK_LABEL: "true"},
            annotations={L.DOCTOR_ANNOTATION: "doc"})
    node = kube.get_node("n1")
    token = b.fold_into_node(node)
    assert len(token) == 2
    kube.replace_node("n1", node)
    b.mark_folded(token)
    assert not b.has_pending()
    assert b.stats()["folded"] == 2
    got = kube.get_node("n1")["metadata"]
    assert got["annotations"][L.EVIDENCE_ANNOTATION] == "ev"
    assert got["labels"][L.DOCTOR_OK_LABEL] == "true"


def test_mark_folded_keeps_newer_generation_pending(kube):
    """A defer landing between fold and mark_folded must stay pending:
    the carrier transported the OLD generation, not the new one."""
    b = NodePatchBatcher(kube, "n1")
    b.defer("evidence", annotations={"a": "old"})
    node = kube.get_node("n1")
    token = b.fold_into_node(node)
    b.defer("evidence", annotations={"a": "new"})  # arrives mid-write
    b.mark_folded(token)
    assert b.has_pending()
    b.flush()
    # flush() used set_node_annotations (annotations-only payload)
    assert kube.get_node("n1")["metadata"]["annotations"]["a"] == "new"


def test_write_labels_now_is_one_patch_carrying_pending(kube):
    b = NodePatchBatcher(kube, "n1")
    b.defer("evidence", annotations={L.EVIDENCE_ANNOTATION: "ev"})
    w0 = kube.node_write_stats()
    b.write_labels_now({L.CC_MODE_STATE_LABEL: "on"})
    w1 = kube.node_write_stats()
    assert w1["requests"] - w0["requests"] == 1  # ONE round trip
    assert w1["mutations"] - w0["mutations"] == 2  # carrying TWO mutations
    meta = kube.get_node("n1")["metadata"]
    assert meta["labels"][L.CC_MODE_STATE_LABEL] == "on"
    assert meta["annotations"][L.EVIDENCE_ANNOTATION] == "ev"
    assert not b.has_pending()


def test_write_labels_now_caller_wins_over_pending(kube):
    """An ordered write's payload is never overridden by a deferred
    mutation under the same key."""
    b = NodePatchBatcher(kube, "n1")
    b.defer("doctor", labels={L.CC_MODE_STATE_LABEL: "stale"})
    b.write_labels_now({L.CC_MODE_STATE_LABEL: "failed"})
    labels = kube.get_node("n1")["metadata"]["labels"]
    assert labels[L.CC_MODE_STATE_LABEL] == "failed"


def test_failed_ordered_write_raises_and_retains_pending(kube):
    """Fail-secure pin: when the combined patch fails, the error
    propagates (the caller owns the failed-state contract), NOTHING
    landed server-side (atomic merge patch), and pending publications
    are retained for the next carrier — no half-applied merge."""
    b = NodePatchBatcher(kube, "n1")
    b.defer("evidence", annotations={L.EVIDENCE_ANNOTATION: "ev"})
    kube.fail_next_node_writes = 1
    with pytest.raises(ApiException) as ei:
        b.write_labels_now({L.CC_MODE_STATE_LABEL: "failed"})
    assert ei.value.status == 429
    meta = kube.get_node("n1")["metadata"]
    assert L.CC_MODE_STATE_LABEL not in (meta.get("labels") or {})
    assert L.EVIDENCE_ANNOTATION not in (meta.get("annotations") or {})
    assert b.has_pending()  # evidence still queued, not lost
    # the retry path still lands the state write AND the evidence
    b.write_labels_now({L.CC_MODE_STATE_LABEL: "failed"})
    meta = kube.get_node("n1")["metadata"]
    assert meta["labels"][L.CC_MODE_STATE_LABEL] == "failed"
    assert meta["annotations"][L.EVIDENCE_ANNOTATION] == "ev"


def test_flush_failure_backs_off_retries_and_accounts(kube):
    retried, dropped = [], []
    b = NodePatchBatcher(kube, "n1", on_retry=retried.append,
                         on_drop=dropped.append)
    b.defer("evidence", annotations={"a": "1"})
    kube.fail_next_node_writes = 3
    assert b.flush() is False
    assert b.stats()["retries"] == 1
    assert retried == ["evidence"]
    # backoff armed: maybe_flush stays quiet until due
    b.maybe_flush()
    assert kube.fail_next_node_writes == 2  # no write attempt happened
    # a forced flush retries through the storm and eventually lands
    assert b.flush() is False
    assert b.flush() is False
    assert b.flush() is True
    assert kube.get_node("n1")["metadata"]["annotations"]["a"] == "1"
    assert b.stats()["retries"] == 3
    assert not dropped


def test_retry_budget_exhaustion_drops_loudly(kube):
    dropped = []
    b = NodePatchBatcher(kube, "n1", on_drop=dropped.append)
    b.defer("evidence", annotations={"a": "1"})
    kube.fail_next_node_writes = NodePatchBatcher.MAX_RETRIES + 1
    for _ in range(NodePatchBatcher.MAX_RETRIES + 1):
        b.flush()
    assert dropped == ["evidence"]
    assert b.stats()["dropped"] == 1
    assert not b.has_pending()  # parked; the owner's gen bookkeeping re-defers


def test_maybe_flush_respects_window_then_delivers(kube):
    b = NodePatchBatcher(kube, "n1", flush_interval_s=0.0)
    b.defer("doctor", annotations={"d": "1"})
    b.maybe_flush()
    assert kube.get_node("n1")["metadata"]["annotations"]["d"] == "1"
    assert not b.has_pending()
    b.maybe_flush()  # nothing pending: no write
    assert kube.node_write_stats()["requests"] == 1
