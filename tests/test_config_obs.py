"""L4 tests: flags-over-env config resolution, metrics rendering, and the
health/metrics HTTP endpoints."""

import urllib.request

import pytest

from tpu_cc_manager.config import AgentConfig, parse_config
from tpu_cc_manager.obs import (
    Counter,
    Gauge,
    HealthServer,
    Histogram,
    Metrics,
    create_readiness_file,
)


# ------------------------------------------------------------------ config
def test_flags_over_env_priority(monkeypatch):
    monkeypatch.setenv("NODE_NAME", "env-node")
    monkeypatch.setenv("DEFAULT_CC_MODE", "off")
    cfg, args = parse_config([])
    assert cfg.node_name == "env-node"
    assert cfg.default_mode == "off"
    # explicit flags beat env (reference cmd/main.go:83-99 EnvVars pattern)
    cfg2, _ = parse_config(["--node-name", "flag-node", "-m", "devtools"])
    assert cfg2.node_name == "flag-node"
    assert cfg2.default_mode == "devtools"


def test_node_name_required(monkeypatch):
    monkeypatch.delenv("NODE_NAME", raising=False)
    with pytest.raises(SystemExit):
        parse_config([])


def test_env_toggles(monkeypatch):
    monkeypatch.setenv("NODE_NAME", "n1")
    monkeypatch.setenv("EVICT_OPERATOR_COMPONENTS", "false")
    monkeypatch.setenv("OPERATOR_NAMESPACE", "custom-ns")
    monkeypatch.setenv("DRAIN_STRATEGY", "node")
    cfg, _ = parse_config([])
    assert cfg.evict_components is False
    assert cfg.operator_namespace == "custom-ns"
    assert cfg.drain_strategy == "node"


def test_invalid_drain_strategy_rejected():
    with pytest.raises(ValueError):
        AgentConfig(node_name="n1", drain_strategy="bogus")


def test_subcommand_parsing(monkeypatch):
    monkeypatch.setenv("NODE_NAME", "n1")
    _, args = parse_config(["set-cc-mode", "-m", "on"])
    assert args.command == "set-cc-mode" and args.mode == "on"
    _, args = parse_config(["get-cc-mode"])
    assert args.command == "get-cc-mode"


# ----------------------------------------------------------------- metrics
def test_counter_and_gauge_render():
    c = Counter("c_total", "help", ("outcome",))
    c.inc("success")
    c.inc("success")
    c.inc("failure")
    text = "\n".join(c.render())
    assert 'c_total{outcome="success"} 2' in text
    assert 'c_total{outcome="failure"} 1' in text

    g = Gauge("g", "help", ("mode",))
    g.set(1.0, "on")
    assert 'g{mode="on"} 1' in "\n".join(g.render())


def test_histogram_buckets_and_quantiles():
    h = Histogram("h_seconds", "help", buckets=(0.1, 1, 10))
    for v in (0.05, 0.5, 5, 50):
        h.observe(v)
    text = "\n".join(h.render())
    assert 'h_seconds_bucket{le="0.1"} 1' in text
    assert 'h_seconds_bucket{le="1"} 2' in text
    assert 'h_seconds_bucket{le="10"} 3' in text
    assert 'h_seconds_bucket{le="+Inf"} 4' in text
    assert "h_seconds_count 4" in text
    assert h.quantile(0.5) == 5  # index 2 of sorted [0.05,0.5,5,50]


def test_metrics_set_current_mode_one_hot():
    m = Metrics()
    m.set_current_mode("on")
    assert m.current_mode.value("on") == 1.0
    assert m.current_mode.value("off") == 0.0
    m.set_current_mode("failed")
    assert m.current_mode.value("on") == 0.0
    assert m.current_mode.value("failed") == 1.0


# ------------------------------------------------------------ health server
def test_health_endpoints():
    m = Metrics()
    m.reconciles_total.inc("success")
    srv = HealthServer(m, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def get(path):
            try:
                with urllib.request.urlopen(base + path) as r:
                    return r.status, r.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, e.read().decode()

        assert get("/healthz")[0] == 200
        assert get("/readyz")[0] == 503  # not ready until initial reconcile
        srv.ready = True
        assert get("/readyz")[0] == 200
        code, body = get("/metrics")
        assert code == 200
        assert 'tpu_cc_reconciles_total{outcome="success"} 1' in body
        assert "tpu_cc_reconcile_duration_seconds_bucket" in body
        assert get("/nope")[0] == 404
    finally:
        srv.stop()


def test_readiness_file(tmp_path):
    path = str(tmp_path / "sub" / ".ready")
    create_readiness_file(path)
    import os

    assert os.path.exists(path)


def test_histogram_quantile_exact_sliding_window():
    from tpu_cc_manager.obs import Histogram

    h = Histogram("h", "t")
    # overflow the window with small values, then fill it with large ones:
    # the quantile must answer over exactly the last WINDOW observations
    for _ in range(Histogram.WINDOW):
        h.observe(0.001)
    for _ in range(Histogram.WINDOW):
        h.observe(100.0)
    assert h.quantile(0.5) == 100.0
    assert h.quantile(0.0) == 100.0  # no pre-window samples leak in
    assert h.count == 2 * Histogram.WINDOW  # cumulative count unaffected


def test_route_server_handler_exception_returns_500():
    import urllib.request

    from tpu_cc_manager.obs import RouteServer

    srv = RouteServer(0, name="t-500").start()
    try:
        srv.add_route("/boom", lambda: 1 / 0)
        srv.add_route("/ok", lambda: (200, b"fine", "text/plain"))
        url = f"http://127.0.0.1:{srv.port}"
        try:
            urllib.request.urlopen(f"{url}/boom")
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 500
            assert b"internal error" in e.read()
        # server still serves other routes afterwards
        with urllib.request.urlopen(f"{url}/ok") as r:
            assert r.read() == b"fine"
    finally:
        srv.stop()
