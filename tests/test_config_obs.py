"""L4 tests: flags-over-env config resolution, metrics rendering, and the
health/metrics HTTP endpoints."""

import urllib.request

import pytest

from tpu_cc_manager.config import AgentConfig, parse_config
from tpu_cc_manager.obs import (
    Counter,
    Gauge,
    HealthServer,
    Histogram,
    Metrics,
    create_readiness_file,
)


# ------------------------------------------------------------------ config
def test_flags_over_env_priority(monkeypatch):
    monkeypatch.setenv("NODE_NAME", "env-node")
    monkeypatch.setenv("DEFAULT_CC_MODE", "off")
    cfg, args = parse_config([])
    assert cfg.node_name == "env-node"
    assert cfg.default_mode == "off"
    # explicit flags beat env (reference cmd/main.go:83-99 EnvVars pattern)
    cfg2, _ = parse_config(["--node-name", "flag-node", "-m", "devtools"])
    assert cfg2.node_name == "flag-node"
    assert cfg2.default_mode == "devtools"


def test_node_name_required(monkeypatch):
    monkeypatch.delenv("NODE_NAME", raising=False)
    with pytest.raises(SystemExit):
        parse_config([])


def test_env_toggles(monkeypatch):
    monkeypatch.setenv("NODE_NAME", "n1")
    monkeypatch.setenv("EVICT_OPERATOR_COMPONENTS", "false")
    monkeypatch.setenv("OPERATOR_NAMESPACE", "custom-ns")
    monkeypatch.setenv("DRAIN_STRATEGY", "node")
    cfg, _ = parse_config([])
    assert cfg.evict_components is False
    assert cfg.operator_namespace == "custom-ns"
    assert cfg.drain_strategy == "node"


def test_invalid_drain_strategy_rejected():
    with pytest.raises(ValueError):
        AgentConfig(node_name="n1", drain_strategy="bogus")


def test_subcommand_parsing(monkeypatch):
    monkeypatch.setenv("NODE_NAME", "n1")
    _, args = parse_config(["set-cc-mode", "-m", "on"])
    assert args.command == "set-cc-mode" and args.mode == "on"
    _, args = parse_config(["get-cc-mode"])
    assert args.command == "get-cc-mode"


# ----------------------------------------------------------------- metrics
def test_counter_and_gauge_render():
    c = Counter("c_total", "help", ("outcome",))
    c.inc("success")
    c.inc("success")
    c.inc("failure")
    text = "\n".join(c.render())
    assert 'c_total{outcome="success"} 2' in text
    assert 'c_total{outcome="failure"} 1' in text

    g = Gauge("g", "help", ("mode",))
    g.set(1.0, "on")
    assert 'g{mode="on"} 1' in "\n".join(g.render())


def test_histogram_buckets_and_quantiles():
    h = Histogram("h_seconds", "help", buckets=(0.1, 1, 10))
    for v in (0.05, 0.5, 5, 50):
        h.observe(v)
    text = "\n".join(h.render())
    assert 'h_seconds_bucket{le="0.1"} 1' in text
    assert 'h_seconds_bucket{le="1"} 2' in text
    assert 'h_seconds_bucket{le="10"} 3' in text
    assert 'h_seconds_bucket{le="+Inf"} 4' in text
    assert "h_seconds_count 4" in text
    assert h.quantile(0.5) == 5  # index 2 of sorted [0.05,0.5,5,50]


def test_metrics_set_current_mode_one_hot():
    m = Metrics()
    m.set_current_mode("on")
    assert m.current_mode.value("on") == 1.0
    assert m.current_mode.value("off") == 0.0
    m.set_current_mode("failed")
    assert m.current_mode.value("on") == 0.0
    assert m.current_mode.value("failed") == 1.0


# ------------------------------------------------------------ health server
def test_health_endpoints():
    m = Metrics()
    m.reconciles_total.inc("success")
    srv = HealthServer(m, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def get(path):
            try:
                with urllib.request.urlopen(base + path) as r:
                    return r.status, r.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, e.read().decode()

        assert get("/healthz")[0] == 200
        assert get("/readyz")[0] == 503  # not ready until initial reconcile
        srv.ready = True
        assert get("/readyz")[0] == 200
        code, body = get("/metrics")
        assert code == 200
        assert 'tpu_cc_reconciles_total{outcome="success"} 1' in body
        assert "tpu_cc_reconcile_duration_seconds_bucket" in body
        assert get("/nope")[0] == 404
    finally:
        srv.stop()


def test_readiness_file(tmp_path):
    path = str(tmp_path / "sub" / ".ready")
    create_readiness_file(path)
    import os

    assert os.path.exists(path)


def test_histogram_quantile_exact_sliding_window():
    from tpu_cc_manager.obs import Histogram

    h = Histogram("h", "t")
    # overflow the window with small values, then fill it with large ones:
    # the quantile must answer over exactly the last WINDOW observations
    for _ in range(Histogram.WINDOW):
        h.observe(0.001)
    for _ in range(Histogram.WINDOW):
        h.observe(100.0)
    assert h.quantile(0.5) == 100.0
    assert h.quantile(0.0) == 100.0  # no pre-window samples leak in
    assert h.count == 2 * Histogram.WINDOW  # cumulative count unaffected


def test_route_server_handler_exception_returns_500():
    import urllib.request

    from tpu_cc_manager.obs import RouteServer

    srv = RouteServer(0, name="t-500").start()
    try:
        srv.add_route("/boom", lambda: 1 / 0)
        srv.add_route("/ok", lambda: (200, b"fine", "text/plain"))
        url = f"http://127.0.0.1:{srv.port}"
        try:
            urllib.request.urlopen(f"{url}/boom")
            assert False, "expected HTTPError"
        except urllib.error.HTTPError as e:
            assert e.code == 500
            assert b"internal error" in e.read()
        # server still serves other routes afterwards
        with urllib.request.urlopen(f"{url}/ok") as r:
            assert r.read() == b"fine"
    finally:
        srv.stop()


# --------------------------------------- metric registration drift guard
def test_every_metric_attribute_is_rendered():
    """ISSUE 8 satellite: render() used to be a hand-maintained list
    and a forgotten metric vanished from /metrics silently. All three
    metric sets now render by reflection (obs.registered_metrics);
    this pins that every metric-primitive attribute appears."""
    from tpu_cc_manager.fleet import FleetMetrics
    from tpu_cc_manager.obs import registered_metrics
    from tpu_cc_manager.policy import PolicyMetrics

    for ms in (Metrics(), FleetMetrics(), PolicyMetrics()):
        text = ms.render()
        prims = registered_metrics(ms)
        assert prims, type(ms).__name__
        for p in prims:
            assert f"# HELP {p.name} " in text, (
                f"{type(ms).__name__}.{p.name} missing from render()"
            )


def test_drift_guard_is_structural_not_a_list():
    """The regression the guard kills: ADD a metric attribute, touch
    nothing else — it must show up in the exposition."""
    m = Metrics()
    m.zz_new_gauge = Gauge("tpu_cc_test_drift_guard", "added in a test")
    assert "tpu_cc_test_drift_guard" in m.render()


def test_counter_set_total_mirrors_external_totals():
    c = Counter("tpu_cc_planner_retraces_total", "x", ("kernel",))
    c.set_total(5, "fleet_tick")
    c.set_total(7, "fleet_tick")
    assert c.value("fleet_tick") == 7
    assert 'tpu_cc_planner_retraces_total{kernel="fleet_tick"} 7' in (
        "\n".join(c.render())
    )


def test_planner_compile_economics_scrapeable():
    """ISSUE 8 satellite: the PR-7 'restart = zero cache misses' claim
    as /metrics surface — plan.compile_stats() mirrored into the fleet
    controller's metric set."""
    from tpu_cc_manager import plan
    from tpu_cc_manager.fleet import FleetMetrics

    stats = plan.compile_stats()
    assert set(stats) == {"retraces", "cache_hits", "cache_misses"}
    assert isinstance(stats["retraces"], dict)
    fm = FleetMetrics()
    fm.planner_retraces.set_total(3, "fleet_tick")
    fm.planner_cache_hits.set_total(2)
    fm.planner_cache_misses.set_total(1)
    text = fm.render()
    assert 'tpu_cc_planner_retraces_total{kernel="fleet_tick"} 3' in text
    assert "tpu_cc_planner_compile_cache_hits_total 2" in text
    assert "tpu_cc_planner_compile_cache_misses_total 1" in text


# --------------------------------------------- exposition-format validation
def test_validate_exposition_accepts_every_live_metric_set():
    from tpu_cc_manager.fleet import FleetMetrics
    from tpu_cc_manager.obs import validate_exposition
    from tpu_cc_manager.policy import PolicyMetrics

    m = Metrics()
    m.reconciles_total.inc("success")
    m.reconcile_duration.observe(0.25)
    m.phase_duration.observe("flip", 0.1)
    m.set_current_mode("on")
    fm = FleetMetrics()
    fm.scan_duration.observe(0.5)
    pm = PolicyMetrics()
    pm.scans.inc()
    for ms in (m, fm, pm):
        assert validate_exposition(ms.render()) == [], type(ms).__name__


def test_validate_exposition_catches_the_bug_classes():
    from tpu_cc_manager.obs import validate_exposition

    def problems(text):
        return validate_exposition(text)

    # duplicate HELP/TYPE (two sets declaring one family)
    dup = (
        "# HELP a_total x\n# TYPE a_total counter\na_total 1\n"
        "# HELP a_total x\n# TYPE a_total counter\n"
    )
    assert any("duplicate HELP" in p for p in problems(dup))
    assert any("duplicate TYPE" in p for p in problems(dup))
    # duplicate series: same name+labels twice
    two = ("# HELP a x\n# TYPE a gauge\n"
           'a{k="v"} 1\na{k="v"} 2\n')
    assert any("duplicate series" in p for p in problems(two))
    # broken label escaping: raw backslash-quote mess
    bad_label = ('# HELP a x\n# TYPE a gauge\n'
                 'a{k="un"quoted"} 1\n')
    assert any("label" in p or "unparseable" in p
               for p in problems(bad_label))
    # a sample with no TYPE declaration
    naked = "orphan_metric 3\n"
    assert any("TYPE" in p for p in problems(naked))
    # non-numeric value
    nan = "# HELP a x\n# TYPE a gauge\na NaNope\n"
    assert any("non-numeric" in p for p in problems(nan))
    # histogram: non-monotone cumulative buckets
    h = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="0.1"} 5\nh_bucket{le="1"} 3\n'
        'h_bucket{le="+Inf"} 5\nh_sum 1\nh_count 5\n'
    )
    assert any("decrease" in p for p in problems(h))
    # histogram: +Inf bucket must equal _count
    h2 = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="0.1"} 1\nh_bucket{le="+Inf"} 2\n'
        "h_sum 1\nh_count 3\n"
    )
    assert any("_count" in p for p in problems(h2))
    # histogram: missing +Inf
    h3 = (
        "# HELP h x\n# TYPE h histogram\n"
        'h_bucket{le="0.1"} 1\nh_sum 1\nh_count 1\n'
    )
    assert any("+Inf" in p for p in problems(h3))


def test_validate_exposition_accepts_escaped_labels():
    from tpu_cc_manager.obs import validate_exposition

    ok = ('# HELP a x\n# TYPE a gauge\n'
          'a{k="with \\"quotes\\" and \\\\"} 1\n')
    assert validate_exposition(ok) == []


# ----------------------------------------------- structured JSON logging
def test_json_log_formatter_injects_trace_ids():
    import json as _json
    import logging

    from tpu_cc_manager.obs import JsonLogFormatter
    from tpu_cc_manager.trace import Tracer

    fmt = JsonLogFormatter()

    def record(msg="hello %s", args=("world",)):
        return logging.LogRecord(
            "tpu-cc-manager.test", logging.INFO, __file__, 1, msg,
            args, None,
        )

    out = _json.loads(fmt.format(record()))
    assert out["msg"] == "hello world"
    assert out["level"] == "INFO"
    assert out["logger"] == "tpu-cc-manager.test"
    assert "trace_id" not in out  # outside any span
    tr = Tracer()
    with tr.span("reconcile") as root:
        inside = _json.loads(fmt.format(record()))
    assert inside["trace_id"] == root.trace_id
    assert inside["span_id"] == root.span_id
    # the adopted-remote case: logs join the CONTROLLER's trace id
    with tr.adopt_remote("00-remotetrace-remotespan-01"):
        with tr.span("reconcile"):
            adopted = _json.loads(fmt.format(record()))
    assert adopted["trace_id"] == "remotetrace"


def test_json_log_formatter_carries_exceptions():
    import json as _json
    import logging
    import sys

    from tpu_cc_manager.obs import JsonLogFormatter

    try:
        raise ValueError("boom")
    except ValueError:
        rec = logging.LogRecord(
            "x", logging.ERROR, __file__, 1, "failed", (),
            sys.exc_info(),
        )
    out = _json.loads(JsonLogFormatter().format(rec))
    assert "ValueError: boom" in out["exc"]


def test_setup_logging_json_opt_in():
    import logging

    from tpu_cc_manager.obs import JsonLogFormatter, setup_logging

    root = logging.getLogger()
    saved_handlers, saved_level = list(root.handlers), root.level
    try:
        setup_logging(False, fmt="json")
        assert any(
            isinstance(h.formatter, JsonLogFormatter)
            for h in root.handlers
        )
    finally:
        for h in list(root.handlers):
            root.removeHandler(h)
        for h in saved_handlers:
            root.addHandler(h)
        root.setLevel(saved_level)


def test_log_format_config_knob(monkeypatch):
    monkeypatch.setenv("NODE_NAME", "n1")
    monkeypatch.setenv("TPU_CC_LOG_FORMAT", "json")
    cfg, _ = parse_config([])
    assert cfg.log_format == "json"
    monkeypatch.delenv("TPU_CC_LOG_FORMAT")
    cfg, _ = parse_config([])
    assert cfg.log_format == "text"
    with pytest.raises(ValueError):
        AgentConfig(node_name="n1", log_format="xml")


def test_flightrec_dir_config_knob(monkeypatch):
    monkeypatch.setenv("NODE_NAME", "n1")
    monkeypatch.setenv("TPU_CC_FLIGHTREC_DIR", "/var/run/flightrec")
    cfg, _ = parse_config([])
    assert cfg.flightrec_dir == "/var/run/flightrec"
    monkeypatch.delenv("TPU_CC_FLIGHTREC_DIR")
    cfg, _ = parse_config([])
    assert cfg.flightrec_dir is None


def test_validate_exposition_never_raises_on_hostile_numerics():
    """The validator's contract is a problem LIST — malformed le labels
    and non-numeric sample values are findings, not crashes (a broken
    live /metrics must fail the smoke check, not traceback it)."""
    from tpu_cc_manager.obs import validate_exposition

    bad_le = ("# HELP h x\n# TYPE h histogram\n"
              'h_bucket{le="abc"} 1\nh_bucket{le="+Inf"} 1\n'
              "h_sum 1\nh_count 1\n")
    probs = validate_exposition(bad_le)
    assert any("non-numeric le" in p for p in probs)
    bad_val = ("# HELP h x\n# TYPE h histogram\n"
               'h_bucket{le="0.1"} oops\nh_bucket{le="+Inf"} 1\n'
               "h_sum 1\nh_count 1\n")
    probs = validate_exposition(bad_val)
    assert any("non-numeric value" in p for p in probs)
