"""Lease-based leader election (tpu_cc_manager.leader) — VERDICT r3
missing #3: two controller replicas must not double-scan or double-
launch rollouts. Mirrors client-go's leaderelection semantics on a
coordination.k8s.io/v1 Lease: CAS acquire/renew, observed-staleness
takeover (never wall-clock comparison), release-on-shutdown for
immediate failover.
"""

import threading
import time

import pytest

from tpu_cc_manager import labels as L
from tpu_cc_manager.k8s.client import ApiException, ConflictError
from tpu_cc_manager.k8s.fake import FakeKube
from tpu_cc_manager.k8s.objects import make_node
from tpu_cc_manager.leader import LeaderElector


def _elector(kube, ident, **kw):
    kw.setdefault("lease_duration_s", 0.4)
    kw.setdefault("renew_period_s", 0.1)
    kw.setdefault("retry_period_s", 0.05)
    return LeaderElector(kube, name="test-lease", identity=ident, **kw)


def _wait(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


# ------------------------------------------------------------ lease CAS
def test_lease_crud_and_cas_fake():
    kube = FakeKube()
    with pytest.raises(ApiException) as ei:
        kube.get_lease("ns", "l")
    assert ei.value.status == 404
    lease = kube.create_lease("ns", {
        "metadata": {"name": "l"},
        "spec": {"holderIdentity": "a"},
    })
    rv = lease["metadata"]["resourceVersion"]
    # same-rv replace lands; the rv moves
    lease2 = kube.replace_lease("ns", "l", lease)
    assert lease2["metadata"]["resourceVersion"] != rv
    # a stale-rv replace is the losing side of the CAS
    with pytest.raises(ConflictError):
        kube.replace_lease("ns", "l", lease)
    with pytest.raises(ApiException) as ei:
        kube.create_lease("ns", {"metadata": {"name": "l"}, "spec": {}})
    assert ei.value.status == 409


def test_lease_over_the_wire():
    """The same trio through the HTTP client against the fake API
    server — the wire contract the real apiserver speaks."""
    from tpu_cc_manager.k8s.apiserver import FakeApiServer
    from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig

    with FakeApiServer() as srv:
        kube = HttpKubeClient(
            KubeConfig(host="127.0.0.1", port=srv.port, use_tls=False)
        )
        with pytest.raises(ApiException) as ei:
            kube.get_lease("tpu-system", "ctl")
        assert ei.value.status == 404
        created = kube.create_lease("tpu-system", {
            "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
            "metadata": {"name": "ctl"},
            "spec": {"holderIdentity": "pod-a"},
        })
        got = kube.get_lease("tpu-system", "ctl")
        assert got["spec"]["holderIdentity"] == "pod-a"
        got["spec"]["holderIdentity"] = "pod-b"
        kube.replace_lease("tpu-system", "ctl", got)
        with pytest.raises(ConflictError):
            kube.replace_lease("tpu-system", "ctl", created)


# ------------------------------------------------------------- election
def test_single_elector_acquires_and_renews():
    kube = FakeKube()
    e = _elector(kube, "a")
    assert e.try_acquire_or_renew() is True
    lease = kube.get_lease("tpu-system", "test-lease")
    assert lease["spec"]["holderIdentity"] == "a"
    first_renew = lease["spec"]["renewTime"]
    time.sleep(0.01)
    assert e.try_acquire_or_renew() is True
    assert kube.get_lease("tpu-system", "test-lease")["spec"][
        "leaseTransitions"] == 0


def test_candidate_waits_out_live_holder_then_takes_over():
    kube = FakeKube()
    a, b = _elector(kube, "a"), _elector(kube, "b")
    assert a.try_acquire_or_renew()
    # b observes a live holder: no takeover while a keeps renewing
    for _ in range(6):
        assert b.try_acquire_or_renew() is False
        assert a.try_acquire_or_renew() is True
        time.sleep(0.08)
    # a dies (stops renewing); b takes over only after the observed
    # renewTime sat unchanged a full lease duration on b's clock
    t0 = time.monotonic()
    assert _wait(lambda: b.try_acquire_or_renew(), timeout=3)
    assert time.monotonic() - t0 >= 0.3  # not instant
    lease = kube.get_lease("tpu-system", "test-lease")
    assert lease["spec"]["holderIdentity"] == "b"
    assert lease["spec"]["leaseTransitions"] == 1
    # the deposed leader's next renew loses the CAS
    assert a.try_acquire_or_renew() is False


def test_create_race_has_one_winner():
    kube = FakeKube()
    results = {}
    barrier = threading.Barrier(2)

    def race(ident):
        e = _elector(kube, ident)
        barrier.wait()
        results[ident] = e.try_acquire_or_renew()

    ts = [threading.Thread(target=race, args=(i,)) for i in ("a", "b")]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sorted(results.values()) == [False, True]


def test_release_on_stop_gives_immediate_failover():
    kube = FakeKube()
    a = _elector(kube, "a").start()
    assert _wait(lambda: a.is_leader)
    b = _elector(kube, "b")
    assert b.try_acquire_or_renew() is False
    a.stop()  # releases the lease
    assert kube.get_lease("tpu-system", "test-lease")["spec"][
        "holderIdentity"] == ""
    # no staleness wait: a released lease is claimed on the next step
    assert b.try_acquire_or_renew() is True


# ----------------------------------------------- controller integration
def _policy(name="pol"):
    return {
        "apiVersion": f"{L.POLICY_GROUP}/{L.POLICY_VERSION}",
        "kind": L.POLICY_KIND,
        "metadata": {"name": name},
        "spec": {"mode": "on",
                 "nodeSelector": L.TPU_ACCELERATOR_LABEL},
    }


def test_two_controllers_exactly_one_scans_and_failover():
    """THE scenario election exists for: two policy controllers over
    one cluster — exactly one scans (no double status writes, no
    double rollout launch); kill the leader and the standby takes over
    within the lease duration and finishes the work."""
    from tpu_cc_manager.policy import PolicyController

    kube = FakeKube()
    kube.add_node(make_node("n1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"}))
    kube.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, _policy())

    # reactive agent so rollouts converge
    stop_agent = threading.Event()

    def agent():
        while not stop_agent.is_set():
            labels = kube.get_node("n1")["metadata"]["labels"]
            want = labels.get(L.CC_MODE_LABEL)
            if want and labels.get(L.CC_MODE_STATE_LABEL) != want:
                kube.set_node_labels("n1",
                                     {L.CC_MODE_STATE_LABEL: want})
            time.sleep(0.02)

    threading.Thread(target=agent, daemon=True).start()

    scans = {"a": 0, "b": 0}

    def make_controller(ident):
        elector = LeaderElector(
            kube, name="tpu-cc-policy-controller", identity=ident,
            lease_duration_s=0.5, renew_period_s=0.1,
            retry_period_s=0.05,
        )
        c = PolicyController(kube, interval_s=0.1, poll_s=0.02,
                             port=0, leader_elector=elector)
        orig = c.scan_once

        def counting(wait_rollout=True):
            scans[ident] += 1
            return orig(wait_rollout=wait_rollout)

        c.scan_once = counting
        return c

    ca, cb = make_controller("a"), make_controller("b")
    ta = threading.Thread(target=ca.run, daemon=True)
    ta.start()
    assert _wait(lambda: scans["a"] > 0)
    tb = threading.Thread(target=cb.run, daemon=True)
    tb.start()
    # give b time to (not) scan while a leads
    time.sleep(1.0)
    assert scans["b"] == 0, "standby must not scan while the leader lives"
    assert cb.healthy  # hot standby stays healthy
    a_scans = scans["a"]
    assert a_scans > 1

    # leader dies; standby takes over and the policy still converges
    ca.stop()
    assert _wait(lambda: scans["b"] > 0, timeout=5), "no failover"
    assert _wait(
        lambda: (kube.get_cluster_custom(
            L.POLICY_GROUP, L.POLICY_VERSION, L.POLICY_PLURAL, "pol"
        ).get("status") or {}).get("phase") == "Converged",
        timeout=5,
    )
    cb.stop()
    stop_agent.set()


def test_demotion_stops_rollout_and_leaves_record_adoptable():
    """A deposed leader must stop ACTING, not just stop scanning: its
    in-flight rollout worker walks away mid-roll, leaving the durable
    record unfinished (heartbeat dead) so the NEW leader adopts and
    finishes it."""
    from tpu_cc_manager.policy import PolicyController
    from tpu_cc_manager.rollout import load_rollout_record

    kube = FakeKube()
    kube.add_node(make_node("n1", labels={
        L.TPU_ACCELERATOR_LABEL: "v5p",
        L.CC_MODE_LABEL: "off", L.CC_MODE_STATE_LABEL: "off"}))
    kube.add_custom(L.POLICY_GROUP, L.POLICY_PLURAL, {
        "apiVersion": f"{L.POLICY_GROUP}/{L.POLICY_VERSION}",
        "kind": L.POLICY_KIND, "metadata": {"name": "pol"},
        "spec": {"mode": "on", "nodeSelector": L.TPU_ACCELERATOR_LABEL,
                 "strategy": {"groupTimeoutSeconds": 60}},
    })
    elector = LeaderElector(kube, name="tpu-cc-policy-controller",
                            identity="a", lease_duration_s=0.5,
                            renew_period_s=0.1, retry_period_s=0.05)
    c = PolicyController(kube, interval_s=0.2, poll_s=0.02, port=0,
                         leader_elector=elector)
    assert elector.try_acquire_or_renew()
    elector._set_leader(True)
    # launch the rollout worker against a pool with NO agent: it would
    # otherwise sit in the 60s group timeout
    r = c.scan_once(wait_rollout=False)
    assert r["policies"]["pol"]["phase"] == "Rolling"
    assert _wait(lambda: any(
        w.get("rollout") is not None for w in c._workers.values()
    ))

    c._on_demoted()  # leadership lost mid-roll
    assert _wait(lambda: not c._workers, timeout=5), \
        "worker did not stop after demotion"
    record, _ = load_rollout_record(kube, kube.list_nodes(None))
    assert record is not None
    assert record["complete"] is False  # adoptable, not finished
    assert record["aborted"] is False

    # the stop is a HANDOFF, not a failure: no Degraded status, no
    # Warning event, no fairness backoff — a brief leadership flap
    # must not penalize a healthy policy for up to 900s
    pol = kube.get_cluster_custom(
        L.POLICY_GROUP, L.POLICY_VERSION, L.POLICY_PLURAL, "pol"
    )
    status = pol.get("status") or {}
    assert status.get("phase") != "Degraded", status
    assert "handed off" in status.get("message", ""), status
    assert "lastRollout" not in status  # the adopter writes the real one
    assert c._retry_after == {}, "handoff must not back the policy off"
    assert c._failures == {}
    reasons = [e.get("reason") for e in kube.cluster_events]
    assert "PolicyRolloutHandedOff" in reasons
    assert "PolicyRolloutAborted" not in reasons
    warning_types = [e.get("type") for e in kube.cluster_events
                     if e.get("reason", "").startswith("PolicyRollout")]
    assert "Warning" not in warning_types


def test_readyz_is_leader_aware():
    """Standby: healthy (liveness ok) but NOT ready — the Service must
    not route /metrics//report scrapes to a replica serving standby
    emptiness."""
    from tpu_cc_manager.policy import PolicyController

    kube = FakeKube()
    elector = _elector(kube, "a")
    c = PolicyController(kube, interval_s=1, port=0,
                         leader_elector=elector)
    assert c._healthz()[0] == 200
    assert c._readyz()[0] == 503  # candidate, not leader yet
    assert b"standby" in c._readyz()[1]
    assert elector.try_acquire_or_renew()
    elector._set_leader(True)
    assert c._readyz()[0] == 200
    # no elector configured: always ready when healthy
    c2 = PolicyController(kube, interval_s=1, port=0)
    assert c2._readyz()[0] == 200


# ------------------------------------------------- lease handoff drills
def test_expiry_race_deposed_holder_demotes_on_cas_loss():
    """The expiry race (ISSUE 11 satellite): the holder's renew and a
    candidate's staleness takeover land on the same lease rv — exactly
    one CAS wins. When the CANDIDATE wins, the old holder's next renew
    must come back False (deposed), never retry into a double-leader."""
    kube = FakeKube()
    a, b = _elector(kube, "a"), _elector(kube, "b")
    assert a.try_acquire_or_renew()
    assert b.try_acquire_or_renew() is False  # b begins observing
    time.sleep(0.45)  # a stops renewing; its lease goes stale on b's clock
    assert b.try_acquire_or_renew() is True  # staleness takeover lands
    # the deposed holder races its renew against b's fresh hold: the
    # CAS rejects it and a must believe the deposition
    assert a.try_acquire_or_renew() is False
    lease = kube.get_lease("tpu-system", "test-lease")
    assert lease["spec"]["holderIdentity"] == "b"
    assert lease["spec"]["leaseTransitions"] == 1
    # and b keeps renewing unharmed
    assert b.try_acquire_or_renew() is True


def test_renew_under_429_holds_then_demotes_after_lease_duration():
    """Renew-under-429 (API-server overload storm): a leader whose
    renewals ERROR (not CAS-lose) stays leader only while its last
    good renew is younger than the lease duration — beyond that it
    must self-demote, because a peer may legitimately have taken
    over."""

    class StormKube(FakeKube):
        def __init__(self):
            super().__init__()
            self.storm = False

        def get_lease(self, ns, name):
            if self.storm:
                raise ApiException(429, "injected lease overload")
            return super().get_lease(ns, name)

        def replace_lease(self, ns, name, lease):
            if self.storm:
                raise ApiException(429, "injected lease overload")
            return super().replace_lease(ns, name, lease)

    kube = StormKube()
    e = _elector(kube, "a").start()
    try:
        assert _wait(lambda: e.is_leader)
        kube.storm = True
        # within the lease duration: benefit of the doubt (flapping on
        # every transient 429 would thrash the controllers)
        time.sleep(0.15)
        assert e.is_leader
        # past the lease duration with no successful renew: demote —
        # acting while unable to prove leadership is the double-writer
        assert _wait(lambda: not e.is_leader, timeout=3), \
            "leader failed to self-demote under a sustained 429 storm"
        # storm ends: the same elector re-acquires (its own stale lease)
        kube.storm = False
        assert _wait(lambda: e.is_leader, timeout=3)
    finally:
        e.stop()


def test_two_candidates_one_lease_exactly_one_takeover():
    """Two candidates watch the same dead holder ripen; both fire the
    takeover CAS in the same window — exactly one must win and the
    loser must return to observing (never claim leadership)."""
    kube = FakeKube()
    holder = _elector(kube, "dead")
    assert holder.try_acquire_or_renew()
    a, b = _elector(kube, "a"), _elector(kube, "b")
    # both start observing the same renewTime
    assert a.try_acquire_or_renew() is False
    assert b.try_acquire_or_renew() is False
    time.sleep(0.45)  # the holder never renews again: staleness ripens

    results = {}
    barrier = threading.Barrier(2)

    def race(ident, elector):
        barrier.wait()
        results[ident] = elector.try_acquire_or_renew()

    ts = [threading.Thread(target=race, args=(i, e))
          for i, e in (("a", a), ("b", b))]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert sorted(results.values()) == [False, True], results
    lease = kube.get_lease("tpu-system", "test-lease")
    assert lease["spec"]["holderIdentity"] in ("a", "b")
    assert lease["spec"]["leaseTransitions"] == 1
    # the loser observed the move and re-observes: no takeover until
    # the NEW holder goes stale for a full duration on its clock
    loser = a if results["a"] is False else b
    assert loser.try_acquire_or_renew() is False


def test_abandon_keeps_lease_for_staleness_takeover():
    """abandon() is the crash simulation (shard-kill drills): the
    lease is NOT released, so a successor pays the full observed-
    staleness wait — unlike stop(), whose release hands off
    immediately."""
    kube = FakeKube()
    a = _elector(kube, "a").start()
    assert _wait(lambda: a.is_leader)
    a.abandon()
    assert not a.is_leader
    lease = kube.get_lease("tpu-system", "test-lease")
    assert lease["spec"]["holderIdentity"] == "a"  # never released
    b = _elector(kube, "b")
    assert b.try_acquire_or_renew() is False  # must observe first
    t0 = time.monotonic()
    assert _wait(lambda: b.try_acquire_or_renew(), timeout=3)
    assert time.monotonic() - t0 >= 0.3  # waited out the staleness


def test_initial_delay_yields_the_create_race():
    """initial_delay_s (shard placement): the handicapped candidate
    does not contest the initial create — the preferred owner wins
    placement — but competes normally afterwards."""
    kube = FakeKube()
    standby = _elector(kube, "standby", initial_delay_s=0.3).start()
    preferred = _elector(kube, "preferred").start()
    try:
        assert _wait(lambda: preferred.is_leader)
        time.sleep(0.5)  # past the standby's handicap
        assert preferred.is_leader
        assert not standby.is_leader
        lease = kube.get_lease("tpu-system", "test-lease")
        assert lease["spec"]["holderIdentity"] == "preferred"
    finally:
        preferred.stop()
        standby.stop()


def test_elector_client_is_never_flow_controlled(monkeypatch):
    """The elector gets its OWN unlimited client when the controller's
    client carries TPU_CC_KUBE_QPS flow control: a lease renewal that
    queues behind throttled scan/rollout traffic past the lease
    duration would self-demote the leader mid-rollout — the classic
    shared-limiter footgun."""
    from tpu_cc_manager.__main__ import _leader_elector
    from tpu_cc_manager.k8s.client import HttpKubeClient, KubeConfig

    monkeypatch.setenv("TPU_CC_LEADER_ELECT", "true")
    monkeypatch.setenv("TPU_CC_KUBE_QPS", "5")
    throttled = HttpKubeClient(KubeConfig("127.0.0.1", 1, use_tls=False))
    assert throttled._bucket is not None  # env limiter is active
    elector = _leader_elector(throttled, "tpu-cc-test-lease")
    assert elector is not None
    assert elector.kube is not throttled
    assert elector.kube._bucket is None  # renewals bypass the bucket
    assert elector.kube.config is throttled.config  # same cluster/auth
