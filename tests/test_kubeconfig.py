"""Kubeconfig parsing + exec-credential (GKE auth path) tests.

The reference gets kubeconfig handling for free from client-go
(cmd/main.go:120) and the kubernetes Python client (main.py:105-114).
Our stdlib KubeConfig must cover the same real-world surface:

- static token users (test clusters, CI);
- inline client-certificate users (legacy admin kubeconfigs);
- ``users[].exec`` credential plugins — the gke-gcloud-auth-plugin path
  that every real GKE kubeconfig uses (no static secret in the file).

The exec tests run a real plugin subprocess (a small Python script) and
prove the full chain over the wire: kubeconfig -> plugin -> bearer token
-> authenticated request against a token-requiring FakeApiServer.
"""

from __future__ import annotations

import base64
import datetime
import json
import os
import sys
import textwrap

import pytest
import yaml

from tpu_cc_manager.k8s.apiserver import FakeApiServer
from tpu_cc_manager.k8s.client import (
    ApiException,
    ExecCredentialError,
    ExecCredentialPlugin,
    HttpKubeClient,
    KubeConfig,
)
from tpu_cc_manager.labels import TPU_ACCELERATOR_LABEL


# --------------------------------------------------------------------------
# fixtures
# --------------------------------------------------------------------------

FAKE_PLUGIN = textwrap.dedent(
    """\
    import json, os, sys

    state = sys.argv[1]
    behavior = sys.argv[2] if len(sys.argv) > 2 else "ok"

    cnt_file = os.path.join(state, "count")
    n = (int(open(cnt_file).read()) if os.path.exists(cnt_file) else 0) + 1
    open(cnt_file, "w").write(str(n))

    info = os.environ.get("KUBERNETES_EXEC_INFO")
    if info:
        open(os.path.join(state, "exec_info"), "w").write(info)

    if behavior == "fail":
        sys.stderr.write("plugin exploded")
        sys.exit(1)
    if behavior == "garbage":
        print("this is not json")
        sys.exit(0)

    tok_file = os.path.join(state, "token")
    token = (
        open(tok_file).read().strip()
        if os.path.exists(tok_file)
        else "tok-%d" % n
    )
    status = {"token": token}
    if behavior == "certs":
        status = {
            "clientCertificateData": "CERT-%d" % n,
            "clientKeyData": "KEY-%d" % n,
        }
    exp_file = os.path.join(state, "expiry")
    if os.path.exists(exp_file):
        status["expirationTimestamp"] = open(exp_file).read().strip()
    if behavior == "empty":
        status = {}
    print(json.dumps({
        "apiVersion": "client.authentication.k8s.io/v1beta1",
        "kind": "ExecCredential",
        "status": status,
    }))
    """
)


@pytest.fixture
def plugin_env(tmp_path):
    """(script_path, state_dir) for the fake credential plugin."""
    script = tmp_path / "fake-gke-auth-plugin.py"
    script.write_text(FAKE_PLUGIN)
    state = tmp_path / "plugin-state"
    state.mkdir()
    return str(script), str(state)


def exec_spec(script, state, behavior="ok", provide_cluster_info=False):
    spec = {
        "apiVersion": "client.authentication.k8s.io/v1beta1",
        "command": sys.executable,
        "args": [script, state, behavior],
        "env": [{"name": "CLOUDSDK_CORE_PROJECT", "value": "tpu-proj"}],
        "interactiveMode": "Never",
    }
    if provide_cluster_info:
        spec["provideClusterInfo"] = True
    return spec


def write_kubeconfig(tmp_path, server, user, cluster_extra=None, name="kc.yaml"):
    """A GKE-shaped kubeconfig: gke_<project>_<zone>_<cluster> naming."""
    cname = "gke_tpu-proj_us-central2-b_tpu-pool"
    cluster = {"server": server}
    cluster.update(cluster_extra or {})
    cfg = {
        "apiVersion": "v1",
        "kind": "Config",
        "current-context": cname,
        "contexts": [{"name": cname, "context": {"cluster": cname, "user": cname}}],
        "clusters": [{"name": cname, "cluster": cluster}],
        "users": [{"name": cname, "user": user}],
    }
    p = tmp_path / name
    p.write_text(yaml.safe_dump(cfg))
    return str(p)


def invocations(state) -> int:
    f = os.path.join(state, "count")
    return int(open(f).read()) if os.path.exists(f) else 0


# --------------------------------------------------------------------------
# static parsing (previously untested: VERDICT r1 weak #7)
# --------------------------------------------------------------------------


class TestKubeconfigParsing:
    def test_static_token(self, tmp_path):
        ca = base64.b64encode(b"CA PEM BYTES").decode()
        p = write_kubeconfig(
            tmp_path,
            "https://34.123.45.67:443",
            {"token": "static-secret"},
            cluster_extra={"certificate-authority-data": ca},
        )
        cfg = KubeConfig.from_kubeconfig(p)
        assert cfg.host == "34.123.45.67"
        assert cfg.port == 443
        assert cfg.use_tls
        assert cfg.bearer_token() == "static-secret"
        assert open(cfg.ca_file, "rb").read() == b"CA PEM BYTES"
        assert cfg.exec_plugin is None

    def test_inline_client_certs(self, tmp_path):
        cert = base64.b64encode(b"CERT PEM").decode()
        key = base64.b64encode(b"KEY PEM").decode()
        p = write_kubeconfig(
            tmp_path,
            "https://10.0.0.1:6443",
            {"client-certificate-data": cert, "client-key-data": key},
        )
        cfg = KubeConfig.from_kubeconfig(p)
        pair = cfg.client_cert_pair()
        assert pair is not None
        assert open(pair[0], "rb").read() == b"CERT PEM"
        assert open(pair[1], "rb").read() == b"KEY PEM"
        assert cfg.bearer_token() is None

    def test_default_port_and_plain_http(self, tmp_path):
        p = write_kubeconfig(tmp_path, "http://localhost", {"token": "t"})
        cfg = KubeConfig.from_kubeconfig(p)
        assert (cfg.use_tls, cfg.port) == (False, 80)

    def test_missing_context_raises_clean_error(self, tmp_path):
        p = write_kubeconfig(tmp_path, "https://x:443", {"token": "t"})
        with pytest.raises(ValueError, match="context 'nope' not found"):
            KubeConfig.from_kubeconfig(p, context="nope")

    def test_exec_user_parsed(self, plugin_env, tmp_path):
        script, state = plugin_env
        p = write_kubeconfig(
            tmp_path, "https://x:443", {"exec": exec_spec(script, state)}
        )
        cfg = KubeConfig.from_kubeconfig(p)
        assert cfg.token is None
        assert cfg.exec_plugin is not None
        assert cfg.exec_plugin.command == sys.executable


# --------------------------------------------------------------------------
# exec plugin behavior
# --------------------------------------------------------------------------


class TestExecCredentialPlugin:
    def test_fetch_and_cache_without_expiry(self, plugin_env):
        script, state = plugin_env
        plugin = ExecCredentialPlugin(exec_spec(script, state))
        assert plugin.token() == "tok-1"
        assert plugin.token() == "tok-1"  # cached: no second invocation
        assert invocations(state) == 1

    def test_expiring_token_is_refreshed(self, plugin_env):
        script, state = plugin_env
        # expiry inside the refresh skew -> never considered fresh
        soon = datetime.datetime.now(datetime.timezone.utc) + datetime.timedelta(
            seconds=ExecCredentialPlugin.REFRESH_SKEW_S // 2
        )
        open(os.path.join(state, "expiry"), "w").write(
            soon.strftime("%Y-%m-%dT%H:%M:%SZ")
        )
        plugin = ExecCredentialPlugin(exec_spec(script, state))
        assert plugin.token() == "tok-1"
        assert plugin.token() == "tok-2"
        assert invocations(state) == 2

    def test_far_expiry_is_cached(self, plugin_env):
        script, state = plugin_env
        later = datetime.datetime.now(datetime.timezone.utc) + datetime.timedelta(
            hours=1
        )
        open(os.path.join(state, "expiry"), "w").write(
            later.strftime("%Y-%m-%dT%H:%M:%SZ")
        )
        plugin = ExecCredentialPlugin(exec_spec(script, state))
        plugin.token()
        plugin.token()
        assert invocations(state) == 1

    def test_env_entries_merged_over_environ(self, plugin_env, tmp_path):
        # the spec's env reaches the plugin (CLOUDSDK_CORE_PROJECT above);
        # prove it via KUBERNETES_EXEC_INFO which only appears when
        # provideClusterInfo is set AND carries the cluster server
        script, state = plugin_env
        plugin = ExecCredentialPlugin(
            exec_spec(script, state, provide_cluster_info=True),
            cluster={"server": "https://34.1.2.3:443",
                     "certificate-authority-data": "Q0E="},
        )
        plugin.token()
        info = json.loads(open(os.path.join(state, "exec_info")).read())
        assert info["kind"] == "ExecCredential"
        assert info["spec"]["cluster"]["server"] == "https://34.1.2.3:443"
        assert info["spec"]["interactive"] is False

    def test_no_cluster_info_by_default(self, plugin_env):
        script, state = plugin_env
        ExecCredentialPlugin(exec_spec(script, state)).token()
        assert not os.path.exists(os.path.join(state, "exec_info"))

    def test_plugin_failure_raises(self, plugin_env):
        script, state = plugin_env
        plugin = ExecCredentialPlugin(exec_spec(script, state, behavior="fail"))
        with pytest.raises(ExecCredentialError, match="plugin exploded"):
            plugin.token()

    def test_garbage_output_raises(self, plugin_env):
        script, state = plugin_env
        plugin = ExecCredentialPlugin(exec_spec(script, state, behavior="garbage"))
        with pytest.raises(ExecCredentialError, match="invalid JSON"):
            plugin.token()

    def test_empty_status_raises(self, plugin_env):
        script, state = plugin_env
        plugin = ExecCredentialPlugin(exec_spec(script, state, behavior="empty"))
        with pytest.raises(ExecCredentialError, match="neither token"):
            plugin.token()

    def test_missing_command_raises(self):
        plugin = ExecCredentialPlugin(
            {"command": "/nonexistent/gke-gcloud-auth-plugin"}
        )
        with pytest.raises(ExecCredentialError, match="not found"):
            plugin.token()

    def test_cert_refresh_reuses_temp_files(self, plugin_env):
        """A short-expiry cert-returning plugin must not grow /tmp: each
        refresh rewrites the same two files in place."""
        script, state = plugin_env
        soon = datetime.datetime.now(datetime.timezone.utc) + datetime.timedelta(
            seconds=ExecCredentialPlugin.REFRESH_SKEW_S // 2
        )
        open(os.path.join(state, "expiry"), "w").write(
            soon.strftime("%Y-%m-%dT%H:%M:%SZ")
        )
        plugin = ExecCredentialPlugin(exec_spec(script, state, behavior="certs"))
        first = plugin.client_cert_pair()
        assert open(first[0]).read() == "CERT-1"
        second = plugin.client_cert_pair()
        assert second == first  # same paths, rewritten in place
        assert open(first[0]).read() == "CERT-2"
        assert open(first[1]).read() == "KEY-2"
        assert invocations(state) == 2


# --------------------------------------------------------------------------
# end-to-end over the wire
# --------------------------------------------------------------------------


def tpu_node(name):
    return {
        "metadata": {
            "name": name,
            "labels": {TPU_ACCELERATOR_LABEL: "tpu-v5p-slice"},
        },
        "spec": {},
        "status": {},
    }


class TestWireAuth:
    def test_exec_kubeconfig_authenticates(self, plugin_env, tmp_path):
        script, state = plugin_env
        open(os.path.join(state, "token"), "w").write("sekrit")
        with FakeApiServer(required_token="sekrit") as srv:
            srv.store.add_node(tpu_node("tpu-node-0"))
            kc = write_kubeconfig(
                tmp_path, srv.url, {"exec": exec_spec(script, state)}
            )
            client = HttpKubeClient(KubeConfig.load(kc))
            node = client.get_node("tpu-node-0")
            assert node["metadata"]["name"] == "tpu-node-0"
            # plugin ran exactly once across requests
            client.list_nodes()
            assert invocations(state) == 1

    def test_wrong_token_is_401(self, plugin_env, tmp_path):
        script, state = plugin_env
        open(os.path.join(state, "token"), "w").write("wrong")
        with FakeApiServer(required_token="sekrit") as srv:
            srv.store.add_node(tpu_node("n0"))
            kc = write_kubeconfig(
                tmp_path, srv.url, {"exec": exec_spec(script, state)}
            )
            client = HttpKubeClient(KubeConfig.load(kc))
            with pytest.raises(ApiException) as ei:
                client.get_node("n0")
            assert ei.value.status == 401

    def test_401_invalidates_and_retries_once(self, plugin_env, tmp_path):
        """A revoked cached token triggers one plugin re-run (client-go
        invalidate-and-retry), transparently to the caller."""
        script, state = plugin_env
        tok_file = os.path.join(state, "token")
        open(tok_file, "w").write("stale")
        with FakeApiServer(required_token="fresh") as srv:
            srv.store.add_node(tpu_node("n0"))
            kc = write_kubeconfig(
                tmp_path, srv.url, {"exec": exec_spec(script, state)}
            )
            cfg = KubeConfig.load(kc)
            cfg.exec_plugin.token()  # prime the cache with the stale token
            open(tok_file, "w").write("fresh")  # rotation happens out-of-band
            client = HttpKubeClient(cfg)
            node = client.get_node("n0")  # 401 -> invalidate -> retry -> 200
            assert node["metadata"]["name"] == "n0"
            assert invocations(state) == 2

    def test_plugin_failure_surfaces_as_api_exception(self, plugin_env, tmp_path):
        """Mid-flight plugin failures must flow through the module's
        ApiException contract (like transport errors) so rollout/agent
        retry-and-rollback handlers catch them."""
        script, state = plugin_env
        with FakeApiServer() as srv:
            srv.store.add_node(tpu_node("n0"))
            kc = write_kubeconfig(
                tmp_path, srv.url,
                {"exec": exec_spec(script, state, behavior="fail")},
            )
            client = HttpKubeClient(KubeConfig.load(kc))
            with pytest.raises(ApiException, match="exec credential failure"):
                client.get_node("n0")
            with pytest.raises(ApiException, match="exec credential failure"):
                for _ in client.watch_nodes(name="n0", timeout_s=1):
                    pass

    def test_watch_401_invalidates_and_retries(self, plugin_env, tmp_path):
        script, state = plugin_env
        tok_file = os.path.join(state, "token")
        open(tok_file, "w").write("stale")
        with FakeApiServer(required_token="fresh") as srv:
            srv.store.add_node(tpu_node("n0"))
            kc = write_kubeconfig(
                tmp_path, srv.url, {"exec": exec_spec(script, state)}
            )
            cfg = KubeConfig.load(kc)
            cfg.exec_plugin.token()  # prime with the stale token
            open(tok_file, "w").write("fresh")
            client = HttpKubeClient(cfg)
            events = list(client.watch_nodes(name="n0", timeout_s=1))
            assert events == []  # clean timeout, not 401
            assert invocations(state) == 2
            # an event arriving on the retried stream is still delivered
            srv.store.patch_node(
                "n0", {"metadata": {"labels": {"x": "y"}}}
            )
            rv = "0"
            etypes = [t for t, _ in client.watch_nodes(
                name="n0", resource_version=rv, timeout_s=1
            )]
            assert "MODIFIED" in etypes

    def test_rollout_cli_via_exec_kubeconfig(self, plugin_env, tmp_path, capsys):
        """The operator-side tool the VERDICT calls out: `rollout`
        authenticating to the API server purely through an exec-plugin
        kubeconfig (no static credentials anywhere)."""
        from tpu_cc_manager.__main__ import main

        script, state = plugin_env
        open(os.path.join(state, "token"), "w").write("sekrit")
        with FakeApiServer(required_token="sekrit") as srv:
            for i in range(3):
                srv.store.add_node(tpu_node(f"tpu-node-{i}"))
            kc = write_kubeconfig(
                tmp_path, srv.url, {"exec": exec_spec(script, state)}
            )
            rc = main(["--kubeconfig", kc, "rollout", "-m", "on", "--dry-run"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out)
        assert report["ok"] is True
        planned = {n for g in report["groups"] for n in g["nodes"]}
        assert planned == {"tpu-node-0", "tpu-node-1", "tpu-node-2"}
