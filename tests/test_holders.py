"""Exclusive-hold guarantee (VERDICT r2 item 3).

The reference's driver unbind makes "device in use mid-flip" impossible
(reference scripts/cc-manager.sh:40-50). Here: /proc fd scan before the
commit — a flip must refuse while a foreign process holds the device
node, a configured runtime-restart hook evicts the holder, and the flip
proceeds once the device is free.
"""

import os
import subprocess
import sys
import time

from tpu_cc_manager.device.fake import FakeBackend, FakeChip
from tpu_cc_manager.device.gate import DeviceGate
from tpu_cc_manager.device.holders import HolderCheck, find_holders
from tpu_cc_manager.engine import ModeEngine


def _hold_device(path):
    """Spawn a process that opens `path` and sleeps; returns the Popen
    once the fd is confirmed open."""
    p = subprocess.Popen(
        [sys.executable, "-c",
         f"import sys,time\nf=open({path!r})\nprint('held',flush=True)\n"
         "time.sleep(120)"],
        stdout=subprocess.PIPE, text=True,
    )
    assert p.stdout.readline().strip() == "held"
    return p


def _dev_file(tmp_path, name="accel0"):
    p = tmp_path / name
    p.write_text("")
    return str(p)


def _engine(backend, states=None, **kw):
    states = states if states is not None else []
    kw.setdefault("evict_components", False)
    kw.setdefault("gate", DeviceGate(enabled=False))
    return ModeEngine(set_state_label=states.append, backend=backend, **kw)


def test_find_holders_sees_foreign_fd_not_own(tmp_path):
    dev = _dev_file(tmp_path)
    assert find_holders(dev) == []
    own = open(dev)
    try:
        assert find_holders(dev) == []  # own fds never count
        p = _hold_device(dev)
        try:
            holders = find_holders(dev)
            assert [h.pid for h in holders] == [p.pid]
            assert holders[0].comm  # readable comm
            assert find_holders(dev, exclude_pids=[p.pid]) == []
        finally:
            p.kill()
            p.wait()
    finally:
        own.close()
    assert find_holders(str(tmp_path / "missing")) == []


def test_flip_refuses_while_device_held(tmp_path):
    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev)
    states = []
    engine = _engine(
        FakeBackend(chips=[chip]), states,
        holder_check=HolderCheck(enabled=True, restart_cmd="",
                                 wait_s=0.5, poll_s=0.1),
    )
    p = _hold_device(dev)
    try:
        assert engine.set_mode("on") is False
        assert states == ["failed"]
        assert chip.query_cc_mode() == "off"  # never committed
        assert chip.resets == 0
    finally:
        p.kill()
        p.wait()
    # holder gone -> the same engine converges
    states.clear()
    assert engine.set_mode("on") is True
    assert states == ["on"]
    assert chip.query_cc_mode() == "on"


def test_restart_hook_evicts_holder_and_flip_proceeds(tmp_path):
    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev)
    p = _hold_device(dev)
    # the configured "runtime restart" kills the external holder, the
    # way `systemctl restart tpu-runtime` would bounce a TPU runtime
    hook = f"kill {p.pid}"
    engine = _engine(
        FakeBackend(chips=[chip]),
        holder_check=HolderCheck(enabled=True, restart_cmd=hook,
                                 wait_s=10, poll_s=0.1),
    )
    try:
        assert engine.set_mode("on") is True
        assert chip.query_cc_mode() == "on"
    finally:
        p.kill()
        p.wait()


def test_failing_restart_hook_fails_the_flip(tmp_path):
    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev)
    states = []
    engine = _engine(
        FakeBackend(chips=[chip]), states,
        holder_check=HolderCheck(enabled=True, restart_cmd="exit 3",
                                 wait_s=0.5, poll_s=0.1),
    )
    p = _hold_device(dev)
    try:
        assert engine.set_mode("on") is False
        assert states == ["failed"]
        assert chip.resets == 0
    finally:
        p.kill()
        p.wait()


def test_holder_check_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_CC_HOLDER_CHECK", "none")
    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev)
    engine = _engine(FakeBackend(chips=[chip]), holder_check=None)
    p = _hold_device(dev)
    try:
        assert engine.set_mode("on") is True  # check skipped
    finally:
        p.kill()
        p.wait()


def test_holder_grace_period_allows_exiting_holder(tmp_path):
    # a holder that lets go within the wait window (no restart hook
    # needed) does not fail the flip
    dev = _dev_file(tmp_path)
    chip = FakeChip(path=dev)
    p = subprocess.Popen(
        [sys.executable, "-c",
         f"import time\nf=open({dev!r})\nprint('held',flush=True)\n"
         "time.sleep(1.0)"],
        stdout=subprocess.PIPE, text=True,
    )
    assert p.stdout.readline().strip() == "held"
    engine = _engine(
        FakeBackend(chips=[chip]),
        holder_check=HolderCheck(enabled=True, restart_cmd="",
                                 wait_s=10, poll_s=0.2),
    )
    t0 = time.monotonic()
    try:
        assert engine.set_mode("on") is True
        assert time.monotonic() - t0 < 10
    finally:
        p.wait()


def test_parallel_flips_run_restart_hook_once(tmp_path):
    """ISSUE 4 thread-safety audit: the restart hook bounces ONE shared
    node-wide runtime. Two parallel flip workers whose devices are held
    by the same process must trigger one restart (serialized + deduped
    by the hook lock's re-scan), not two racing ones."""
    dev_a = _dev_file(tmp_path, "accel0")
    dev_b = _dev_file(tmp_path, "accel1")
    # one "runtime" process holding BOTH chips
    p = subprocess.Popen(
        [sys.executable, "-c",
         f"import time\na=open({dev_a!r}); b=open({dev_b!r})\n"
         "print('held', flush=True)\ntime.sleep(120)"],
        stdout=subprocess.PIPE, text=True,
    )
    assert p.stdout.readline().strip() == "held"
    count = tmp_path / "hook-count"
    # SIGKILL + teardown margin: by the time the second worker's re-scan
    # runs (it waits on the hook lock for this command to finish), the
    # holder is verifiably gone
    hook = f"echo x >> {count} && kill -9 {p.pid} && sleep 0.3"
    chips = [FakeChip(path=dev_a), FakeChip(path=dev_b)]
    engine = _engine(
        FakeBackend(chips=chips),
        holder_check=HolderCheck(enabled=True, restart_cmd=hook,
                                 wait_s=10, poll_s=0.1),
        flip_concurrency=2,
    )
    try:
        assert engine.set_mode("on") is True
    finally:
        p.kill()
        p.wait()
    assert count.read_text().count("x") == 1
    assert all(c.query_cc_mode() == "on" for c in chips)
